"""tpu-lint rule set: the hazard classes this codebase actually has.

Each rule is registered with a name (the suppression/baseline handle),
a severity, and a one-line summary (``--list-rules``). Module rules run
per file over a :class:`~apex_tpu.analysis.walker.ModuleIndex`; project
rules run once over the repo root (cross-file drift checks).

Design bias: precision over recall. Every check fires only on patterns
it can resolve statically (literal block shapes, module-local jit
wrappers, named parameters) — a lint that cries wolf on ``tile``-shaped
variables it cannot evaluate would be suppressed into uselessness within
two PRs. The expensive hazards (host syncs in the decode scan, Mosaic
tiling violations) all show up in exactly these resolvable forms.
"""

from __future__ import annotations

import ast
import dataclasses
import re
from pathlib import Path
from typing import Callable, Dict, Iterator, List, Optional, Set, Tuple

from apex_tpu.analysis.walker import (Finding, FunctionInfo, ModuleIndex,
                                      call_name, const_int_tuple,
                                      const_str_tuple, dotted_name,
                                      host_callback_exempt_ids, kwarg,
                                      name_tail, walk_shallow)


@dataclasses.dataclass(frozen=True)
class Rule:
    name: str
    severity: str
    summary: str
    check: Callable
    project: bool = False    # True: check(root) once, not per module


RULES: Dict[str, Rule] = {}


def rule(name: str, severity: str, summary: str, project: bool = False):
    def deco(fn):
        RULES[name] = Rule(name=name, severity=severity, summary=summary,
                           check=fn, project=project)
        return fn
    return deco


# --------------------------------------------------------------------------
# 1. host-sync-in-jit
# --------------------------------------------------------------------------

_DEVICE_GET = {"jax.device_get", "device_get"}
_NP_HOST = {"np.asarray", "np.array", "numpy.asarray", "numpy.array",
            "onp.asarray", "onp.array"}
_PY_CASTS = {"float", "int", "bool"}
#: numpy scalar-constructor coercions: ``np.float32(traced)`` /
#: ``np.int32(traced)`` concretize exactly like ``float(traced)`` but
#: ride a dotted name, so the bare-cast check above misses them
_NP_MODS = {"np", "numpy", "onp"}
_NP_SCALAR_CASTS = {"float16", "float32", "float64", "bfloat16", "half",
                    "single", "double", "longdouble", "int8", "int16",
                    "int32", "int64", "uint8", "uint16", "uint32",
                    "uint64", "intp", "bool_"}


def _positional_params(info: FunctionInfo) -> Set[str]:
    a = info.node.args
    return {p.arg for p in (a.posonlyargs + a.args)}


@rule("host-sync-in-jit", "error",
      "device->host sync (.item()/np.asarray/device_get/float(traced)) "
      "reachable from a jitted function or scan/while body "
      "(jax.debug.callback / metrics.record payloads are exempt: the "
      "callback runs host-side after the step, without blocking it)")
def check_host_sync(mi: ModuleIndex) -> Iterator[Finding]:
    r = RULES["host-sync-in-jit"]
    for info, chain in mi.jit_reachable():
        params = _positional_params(info)
        # the callable handed to jax.debug.callback executes on the host
        # with delivered (not traced) values — host ops inside it are the
        # POINT, not a sync. Only the callable argument is exempt: traced
        # operands of the callback keep full scrutiny.
        exempt = host_callback_exempt_ids(info.node)
        for node in walk_shallow(info.node):
            if not isinstance(node, ast.Call) or id(node) in exempt:
                continue
            why = None
            if isinstance(node.func, ast.Attribute):
                if node.func.attr == "item" and not node.args:
                    why = "`.item()` forces a device->host transfer"
                elif node.func.attr == "block_until_ready":
                    why = "`.block_until_ready()` blocks on the device"
            cn = call_name(node)
            if cn in _DEVICE_GET:
                why = "`jax.device_get` copies device->host"
            elif cn in _NP_HOST:
                why = f"`{cn}` materializes a traced value on the host"
            elif cn in _PY_CASTS and len(node.args) == 1 \
                    and isinstance(node.args[0], ast.Name) \
                    and node.args[0].id in params:
                why = (f"`{cn}({node.args[0].id})` on a traced argument "
                       "concretizes it on the host")
            elif cn and "." in cn and len(node.args) == 1 \
                    and isinstance(node.args[0], ast.Name) \
                    and node.args[0].id in params:
                mod, tail = cn.rsplit(".", 1)
                if mod in _NP_MODS and tail in _NP_SCALAR_CASTS:
                    why = (f"`{cn}({node.args[0].id})` on a traced "
                           "argument materializes it as a host scalar")
            if why:
                yield mi.finding(
                    r, node,
                    f"{why} inside `{info.qualname}` "
                    f"(traced via: {' -> '.join(chain)})")


# --------------------------------------------------------------------------
# 2-4. Pallas kernel contracts
# --------------------------------------------------------------------------

def _is_call_tail(node: ast.AST, tail: str) -> bool:
    if not isinstance(node, ast.Call):
        return False
    cn = call_name(node)
    return cn is not None and cn.split(".")[-1] == tail


def _pallas_calls(mi: ModuleIndex) -> List[ast.Call]:
    return [n for n in ast.walk(mi.tree) if _is_call_tail(n, "pallas_call")]


def _grid_spec_calls(mi: ModuleIndex) -> List[ast.Call]:
    out = []
    for n in ast.walk(mi.tree):
        if isinstance(n, ast.Call):
            cn = call_name(n)
            if cn and cn.split(".")[-1].endswith("GridSpec"):
                out.append(n)
    return out


def _grid_arity(container: ast.Call) -> Optional[int]:
    """Number of index_map args the container's grid implies, counting
    scalar-prefetch operands (PrefetchScalarGridSpec prepends them)."""
    grid = kwarg(container, "grid")
    if grid is None:
        return None
    if isinstance(grid, (ast.Tuple, ast.List)):
        n = len(grid.elts)
    elif isinstance(grid, ast.Constant) and isinstance(grid.value, int):
        n = 1
    else:
        return None
    nsp = kwarg(container, "num_scalar_prefetch")
    if nsp is not None:
        if isinstance(nsp, ast.Constant) and isinstance(nsp.value, int):
            n += nsp.value
        else:
            return None
    return n


def _block_specs(container: ast.Call,
                 skip: Optional[ast.AST] = None) -> Iterator[ast.Call]:
    skipped = set()
    if skip is not None:
        skipped = {id(x) for x in ast.walk(skip)}
    for node in ast.walk(container):
        if id(node) in skipped or node is container:
            continue
        if _is_call_tail(node, "BlockSpec"):
            yield node


def _spec_containers(mi: ModuleIndex) -> Iterator[Tuple[ast.Call,
                                                        Optional[int]]]:
    """Yield (container, expected index_map arity) for every pallas_call /
    *GridSpec carrying BlockSpecs. BlockSpecs inside an inline grid_spec=
    argument are attributed to the GridSpec container, not the call."""
    for gs in _grid_spec_calls(mi):
        yield gs, _grid_arity(gs)
    for pc in _pallas_calls(mi):
        yield pc, _grid_arity(pc)


@rule("pallas-index-map-arity", "error",
      "BlockSpec index_map parameter count disagrees with the grid rank "
      "(+ scalar-prefetch operands)")
def check_index_map_arity(mi: ModuleIndex) -> Iterator[Finding]:
    r = RULES["pallas-index-map-arity"]
    for container, arity in _spec_containers(mi):
        if arity is None:
            continue
        gs = kwarg(container, "grid_spec")
        for spec in _block_specs(container, skip=gs):
            index_map = (spec.args[1] if len(spec.args) > 1
                         else kwarg(spec, "index_map"))
            if not isinstance(index_map, ast.Lambda):
                continue
            a = index_map.args
            if a.vararg is not None or a.kwarg is not None:
                continue             # lambda *g: ... adapts to any grid
            got = len(a.posonlyargs + a.args)
            if got != arity:
                yield mi.finding(
                    r, index_map,
                    f"index_map takes {got} arg(s) but the grid supplies "
                    f"{arity} (grid rank + num_scalar_prefetch) — Pallas "
                    "will raise at trace time or silently mis-index")


_SMEM_LIKE = {"SMEM", "ANY", "SEMAPHORE"}
_LANE = 128
_SUBLANE = 8     # fp32 floor; bf16 needs 16, int8/fp8 32 — 8 is the
                 # universal minimum any literal block must clear


@rule("pallas-block-tiling", "warning",
      "literal BlockSpec block shape is not a multiple of the TPU tile "
      "(sublane multiple x 128 lanes)")
def check_block_tiling(mi: ModuleIndex) -> Iterator[Finding]:
    r = RULES["pallas-block-tiling"]
    for container, _ in _spec_containers(mi):
        gs = kwarg(container, "grid_spec")
        for spec in _block_specs(container, skip=gs):
            mem = kwarg(spec, "memory_space")
            if mem is not None and (name_tail(mem) or "") in _SMEM_LIKE:
                continue          # scalar/control blocks are untiled
            shape = (spec.args[0] if spec.args
                     else kwarg(spec, "block_shape"))
            if not isinstance(shape, (ast.Tuple, ast.List)) \
                    or not shape.elts:
                continue

            def lit(e):
                return e.value if (isinstance(e, ast.Constant)
                                   and isinstance(e.value, int)) else None

            lane = lit(shape.elts[-1])
            # minor dim 1 is a degenerate stat column (Mosaic pads it);
            # anything else literal must fill whole 128-lane registers
            if lane is not None and lane != 1 and lane % _LANE:
                yield mi.finding(
                    r, shape,
                    f"minor (lane) block dim {lane} is not a multiple of "
                    f"{_LANE}; Mosaic pads every tile — size it "
                    f"{_LANE}*k or 1")
            if len(shape.elts) >= 2:
                sub = lit(shape.elts[-2])
                if sub is not None and sub != 1 and sub % _SUBLANE:
                    yield mi.finding(
                        r, shape,
                        f"second-minor (sublane) block dim {sub} is not a "
                        f"multiple of {_SUBLANE} (fp32 floor; bf16 needs "
                        "16, int8/fp8 32)")


_DTYPE_NAMES = {
    "float32", "float16", "bfloat16", "float64", "float8_e4m3fn",
    "float8_e5m2", "int8", "int16", "int32", "int64", "uint8", "uint32",
    "bool_",
}


@rule("pallas-dtype-drift", "warning",
      "pallas_call out_shape copies an input's .shape but hard-codes the "
      "dtype — drifts when the input dtype changes")
def check_dtype_drift(mi: ModuleIndex) -> Iterator[Finding]:
    r = RULES["pallas-dtype-drift"]
    for pc in _pallas_calls(mi):
        out_shape = kwarg(pc, "out_shape")
        if out_shape is None:
            continue
        for node in ast.walk(out_shape):
            if not _is_call_tail(node, "ShapeDtypeStruct"):
                continue
            shape = node.args[0] if node.args else None
            dtype = (node.args[1] if len(node.args) > 1
                     else kwarg(node, "dtype"))
            if not (isinstance(shape, ast.Attribute)
                    and shape.attr == "shape"
                    and isinstance(shape.value, ast.Name)):
                continue
            if isinstance(dtype, ast.Attribute) \
                    and dtype.attr in _DTYPE_NAMES:
                src = shape.value.id
                yield mi.finding(
                    r, node,
                    f"out_shape mirrors `{src}.shape` but pins dtype "
                    f"`{dotted_name(dtype)}` — use `{src}.dtype` (or "
                    "suppress if the widening is intentional)")


@rule("pallas-traced-branch", "error",
      "Python `if`/`while` on a value loaded from a kernel ref — traced "
      "values need jnp.where / pl.when, not host control flow")
def check_traced_branch(mi: ModuleIndex) -> Iterator[Finding]:
    r = RULES["pallas-traced-branch"]
    kernels = [info for info in mi.functions.values()
               if "pallas kernel" in info.jit_reasons]
    for info in kernels:
        params = _positional_params(info)
        for node in walk_shallow(info.node):
            if not isinstance(node, (ast.If, ast.While)):
                continue
            for sub in ast.walk(node.test):
                if isinstance(sub, ast.Subscript) \
                        and isinstance(sub.value, ast.Name) \
                        and sub.value.id in params:
                    yield mi.finding(
                        r, node,
                        f"branch on `{ast.unparse(sub)}` inside kernel "
                        f"`{info.qualname}` — ref loads are traced; use "
                        "`@pl.when` or `jnp.where`")
                    break


# --------------------------------------------------------------------------
# 5-6. recompile hazards
# --------------------------------------------------------------------------

def _jit_wrappers(mi: ModuleIndex, local_only: bool = False
                  ) -> Dict[str, dict]:
    """Callables known to be jit-wrapped, with their static and donated
    argument positions (literal kwargs only): module-local assignments /
    decorators, plus — unless ``local_only`` — wrappers IMPORTED from
    other scanned modules (injected by project.ProjectIndex, keyed by the
    importing name; local definitions shadow them)."""
    wrappers: Dict[str, dict] = {} if local_only \
        else dict(getattr(mi, "extra_wrappers", {}))

    def record(tail: Optional[str], jit_call: ast.Call):
        if not tail:
            return
        info = {"static_pos": (), "static_names": (), "donate_pos": (),
                "node": jit_call}
        v = kwarg(jit_call, "static_argnums")
        if v is not None:
            info["static_pos"] = const_int_tuple(v) or ()
        v = kwarg(jit_call, "static_argnames")
        if v is not None:
            info["static_names"] = const_str_tuple(v) or ()
        v = kwarg(jit_call, "donate_argnums")
        if v is not None:
            info["donate_pos"] = const_int_tuple(v) or ()
        if info["static_pos"] or info["static_names"] \
                or info["donate_pos"]:
            wrappers[tail] = info

    for node in ast.walk(mi.tree):
        if isinstance(node, ast.Assign) and len(node.targets) == 1:
            cn = node.value if isinstance(node.value, ast.Call) else None
            if cn is not None and call_name(cn) \
                    and call_name(cn).split(".")[-1] == "jit":
                record(name_tail(node.targets[0]), cn)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                if isinstance(dec, ast.Call):
                    cn = call_name(dec)
                    tail = cn.split(".")[-1] if cn else None
                    if tail == "jit":
                        record(node.name, dec)
                    elif tail == "partial" and dec.args:
                        inner = dotted_name(dec.args[0])
                        if inner and inner.split(".")[-1] == "jit":
                            record(node.name, dec)
    return wrappers


_FRESH_CTORS = {"list", "dict", "set"}
_ARRAY_CTORS = {"array", "asarray", "zeros", "ones", "arange", "full",
                "empty"}
_ARRAY_MODS = {"np", "jnp", "numpy", "onp"}


def _is_unhashable_arg(node: ast.AST) -> Optional[str]:
    if isinstance(node, (ast.List, ast.ListComp)):
        return "a list"
    if isinstance(node, (ast.Dict, ast.DictComp)):
        return "a dict"
    if isinstance(node, (ast.Set, ast.SetComp)):
        return "a set"
    if isinstance(node, ast.GeneratorExp):
        return "a generator"
    if isinstance(node, ast.Call):
        cn = call_name(node)
        if cn in _FRESH_CTORS:
            return f"a fresh `{cn}()`"
        if cn and "." in cn:
            mod, tail = cn.rsplit(".", 1)
            if mod in _ARRAY_MODS and tail in _ARRAY_CTORS:
                return f"an `{cn}` array"
    return None


@rule("jit-unhashable-static", "error",
      "unhashable / freshly-constructed object flows into a "
      "static_argnums|static_argnames position — TypeError at best, "
      "recompile-per-call at worst")
def check_unhashable_static(mi: ModuleIndex) -> Iterator[Finding]:
    r = RULES["jit-unhashable-static"]
    wrappers = {t: w for t, w in _jit_wrappers(mi).items()
                if w["static_pos"] or w["static_names"]}

    def check_site(call: ast.Call, w: dict, label: str):
        for pos in w["static_pos"]:
            if 0 <= pos < len(call.args):
                what = _is_unhashable_arg(call.args[pos])
                if what:
                    yield mi.finding(
                        r, call.args[pos],
                        f"{what} is passed at static position {pos} of "
                        f"`{label}` — static args are hashed into the "
                        "compile key")
        for kw in call.keywords:
            if kw.arg in w["static_names"]:
                what = _is_unhashable_arg(kw.value)
                if what:
                    yield mi.finding(
                        r, kw.value,
                        f"{what} is passed as static arg "
                        f"`{kw.arg}` of `{label}`")

    for node in ast.walk(mi.tree):
        if not isinstance(node, ast.Call):
            continue
        tail = name_tail(node.func)
        if tail in wrappers:
            yield from check_site(node, wrappers[tail], tail)
        # immediate invocation: jax.jit(f, static_argnums=...)(args)
        if isinstance(node.func, ast.Call):
            inner = node.func
            cn = call_name(inner)
            if cn and cn.split(".")[-1] == "jit":
                sa = kwarg(inner, "static_argnums")
                sn = kwarg(inner, "static_argnames")
                w = {"static_pos":
                     const_int_tuple(sa) or () if sa is not None else (),
                     "static_names":
                     const_str_tuple(sn) or () if sn is not None else ()}
                if w["static_pos"] or w["static_names"]:
                    yield from check_site(node, w, cn)


_COMPILE_CACHE_NAME = re.compile(r"jit|compil")


@rule("compile-key-unbounded", "warning",
      "compile-cache key built from an f-string / str() of a runtime "
      "value — unbounded key set means unbounded compiles (bucket it, "
      "like the prefix cache's power-of-two match flooring)")
def check_compile_key(mi: ModuleIndex) -> Iterator[Finding]:
    r = RULES["compile-key-unbounded"]

    def stringy(node: ast.AST) -> bool:
        for sub in ast.walk(node):
            if isinstance(sub, ast.JoinedStr):
                return True
            if isinstance(sub, ast.Call) and call_name(sub) in ("str",
                                                                "repr"):
                return True
        return False

    for node in ast.walk(mi.tree):
        if isinstance(node, ast.Subscript):
            tail = name_tail(node.value)
            if tail and _COMPILE_CACHE_NAME.search(tail) \
                    and stringy(node.slice):
                yield mi.finding(
                    r, node,
                    f"`{tail}[...]` is keyed on a stringified runtime "
                    "value — every distinct value is a fresh XLA "
                    "compile; floor/bucket the key instead")
        elif isinstance(node, ast.Call) \
                and isinstance(node.func, ast.Attribute) \
                and node.func.attr in ("setdefault", "get") and node.args:
            tail = name_tail(node.func.value)
            if tail and _COMPILE_CACHE_NAME.search(tail) \
                    and stringy(node.args[0]):
                yield mi.finding(
                    r, node.args[0],
                    f"`{tail}.{node.func.attr}(...)` key is a stringified "
                    "runtime value — bucket it to bound the compile set")


# --------------------------------------------------------------------------
# 7. jit-donated-reuse
# --------------------------------------------------------------------------

def _expr_key(node: ast.AST) -> Optional[tuple]:
    """ctx-insensitive identity for Name/Attribute chains."""
    if isinstance(node, ast.Name):
        return ("n", node.id)
    if isinstance(node, ast.Attribute):
        base = _expr_key(node.value)
        return ("a", base, node.attr) if base else None
    return None


def _assign_targets(stmt: ast.stmt) -> List[tuple]:
    keys: List[tuple] = []
    targets: List[ast.AST] = []
    if isinstance(stmt, ast.Assign):
        targets = list(stmt.targets)
    elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
        targets = [stmt.target]
    for t in targets:
        if isinstance(t, (ast.Tuple, ast.List)):
            targets.extend(t.elts)
        else:
            k = _expr_key(t)
            if k:
                keys.append(k)
    return keys


def _blocks(root: ast.AST) -> Iterator[List[ast.stmt]]:
    if hasattr(root, "body") and isinstance(root.body, list):
        yield root.body
    for node in walk_shallow(root):
        for attr in ("body", "orelse", "finalbody"):
            blk = getattr(node, attr, None)
            if isinstance(blk, list) and blk \
                    and isinstance(blk[0], ast.stmt):
                yield blk


def _header_walk(stmt: ast.stmt) -> Iterator[ast.AST]:
    """Expressions belonging to ``stmt`` itself — sub-statement bodies
    (and nested defs) are other blocks and analyzed there."""
    stack: List[ast.AST] = [stmt]
    while stack:
        node = stack.pop()
        yield node
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.stmt):
                continue
            stack.append(child)


def _scope_walk(stmt: ast.stmt) -> Iterator[ast.AST]:
    """``ast.walk`` that stays in the current runtime scope: nested
    function/class bodies and lambdas run later (or never)."""
    stack: List[ast.AST] = [stmt]
    while stack:
        node = stack.pop()
        if node is not stmt and isinstance(
                node, (ast.FunctionDef, ast.AsyncFunctionDef,
                       ast.ClassDef, ast.Lambda)):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


@rule("jit-donated-reuse", "error",
      "buffer passed through donate_argnums is read again after the "
      "call — the donated buffer is invalidated on TPU")
def check_donated_reuse(mi: ModuleIndex) -> Iterator[Finding]:
    r = RULES["jit-donated-reuse"]
    wrappers = {t: w for t, w in _jit_wrappers(mi).items()
                if w["donate_pos"]}
    if not wrappers:
        return
    roots: List[ast.AST] = [mi.tree] + [f.node
                                        for f in mi.functions.values()]
    for root in roots:
        for block in _blocks(root):
            for i, stmt in enumerate(block):
                for node in _header_walk(stmt):
                    if not isinstance(node, ast.Call):
                        continue
                    tail = name_tail(node.func)
                    if tail not in wrappers:
                        continue
                    donated = [
                        _expr_key(node.args[p])
                        for p in wrappers[tail]["donate_pos"]
                        if 0 <= p < len(node.args)]
                    donated = [d for d in donated if d]
                    if not donated:
                        continue
                    rebound = set(_assign_targets(stmt))
                    live = [d for d in donated if d not in rebound]
                    yield from _scan_after(mi, r, tail, block[i + 1:],
                                           live)


def _scan_after(mi: ModuleIndex, r: Rule, callee: str,
                rest: List[ast.stmt], live: List[tuple]
                ) -> Iterator[Finding]:
    live = list(live)
    for stmt in rest:
        if not live:
            return
        rebound = set(_assign_targets(stmt))
        for node in _scope_walk(stmt):
            k = _expr_key(node)
            if k in live and isinstance(getattr(node, "ctx", None),
                                        ast.Load):
                yield mi.finding(
                    r, node,
                    f"`{ast.unparse(node)}` was donated to `{callee}` "
                    "above and may alias freed memory — rebind the "
                    "result or drop the donation")
                live.remove(k)
        live = [d for d in live if d not in rebound]


# --------------------------------------------------------------------------
# 8. aot-case-drift (project rule)
# --------------------------------------------------------------------------

@rule("aot-case-drift", "error",
      "tests/test_aot_mosaic.py CASE_NAMES names a case tpu_aot.py "
      "kernel_cases() no longer yields", project=True)
def check_aot_case_drift(root: Path) -> Iterator[Finding]:
    r = RULES["aot-case-drift"]
    aot = root / "tpu_aot.py"
    ci = root / "tests" / "test_aot_mosaic.py"
    if not aot.exists() or not ci.exists():
        return

    try:
        aot_tree = ast.parse(aot.read_text(), filename=str(aot))
        ci_tree = ast.parse(ci.read_text(), filename=str(ci))
    except SyntaxError:
        return                      # parse errors are reported per-file

    yielded: Set[str] = set()
    for node in ast.walk(aot_tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                and node.name == "kernel_cases":
            for sub in ast.walk(node):
                if isinstance(sub, ast.Yield) \
                        and isinstance(sub.value, ast.Tuple) \
                        and sub.value.elts \
                        and isinstance(sub.value.elts[0], ast.Constant) \
                        and isinstance(sub.value.elts[0].value, str):
                    yielded.add(sub.value.elts[0].value)

    ci_rel = ci.relative_to(root).as_posix()
    for node in ast.walk(ci_tree):
        if not (isinstance(node, ast.Assign)
                and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and node.targets[0].id == "CASE_NAMES"):
            continue
        if not isinstance(node.value, (ast.List, ast.Tuple)):
            continue
        if not yielded:
            yield Finding(
                rule=r.name, severity=r.severity, path=ci_rel,
                line=node.lineno, col=node.col_offset + 1,
                scope="CASE_NAMES",
                message="tpu_aot.kernel_cases() yields no statically "
                        "visible case names — the CI tier cannot be "
                        "checked for drift")
            return
        for elt in node.value.elts:
            if isinstance(elt, ast.Constant) \
                    and isinstance(elt.value, str) \
                    and elt.value not in yielded:
                yield Finding(
                    rule=r.name, severity=r.severity, path=ci_rel,
                    line=elt.lineno, col=elt.col_offset + 1,
                    scope="CASE_NAMES",
                    message=f"CI case `{elt.value}` is not yielded by "
                            "tpu_aot.kernel_cases() — the pair drifted "
                            "(PR 1 and PR 2 both had to sync it by hand)")


def module_rules() -> List[Rule]:
    return [r for r in RULES.values() if not r.project]


def project_rules() -> List[Rule]:
    return [r for r in RULES.values() if r.project]
