"""Contract-index extraction: every string-keyed producer and consumer.

The stack's wire surface is held together by NAMES — metric families,
event kinds, HTTP routes, SSE frame kinds, schema-version literals,
pinned field tuples, ledger gating classes — and none of the other
tiers can see two of them drift apart. This module builds the
repo-wide :class:`ContractIndex` the ``contract-*`` rules check:

- **python producers** (stdlib ``ast`` over the already-parsed
  ``ModuleIndex`` trees, zero imports executed): every
  ``metrics.counter/gauge/histogram`` registration with its statically
  resolved family name and label-key set, every ``EventLog.emit`` kind,
  the HTTP route dispatch comparisons and raw client request paths,
  ``_sse(...)`` frame emissions, ``apex-tpu/...`` schema constants with
  their writer stamps and validator comparisons, and every
  module-level tuple-of-strings constant (the report field pins and the
  ledger extraction/gating tuples);
- **python consumers**: literal ``e["kind"] ==`` / ``.get("kind") ==``
  comparisons (NOT ``.kind`` attribute reads — ``FaultSpec.kind`` is a
  fault name, not an event kind) and the SSE client's
  ``event == "..."`` parse arms;
- **text consumers**: the instrument/event catalogs of
  ``docs/observability.md``, the endpoint table of ``docs/http.md``,
  and the family names pinned by ``tests/golden/observability.prom``
  — parsed from their markdown tables / ``# TYPE`` lines.

Same precision bias as every other tier: a name is indexed only when it
is statically resolvable — a string literal, an f-string over a
comprehension/loop variable bound to a literal tuple (possibly a
module-level or imported constant: ``f"serving.{name}" for name in
_RUN_COUNTERS``), or a dict-literal ``.items()`` loop. A
counter/gauge/histogram registration whose name CANNOT be resolved is
itself recorded (``ContractIndex.unresolved_metrics``) — the
undocumented-metric rule reports it, so the wire surface stays
statically auditable by construction. The raw ``metrics.record``
series is deliberately out of scope: it banks run-stats trajectory
keyed by dynamic stats dicts, not cataloged instruments.
"""

from __future__ import annotations

import ast
import dataclasses
import re
from typing import Dict, FrozenSet, List, Optional, Sequence, Set, Tuple

from apex_tpu.analysis.walker import ModuleIndex, name_tail

_INSTRUMENT_KINDS = ("counter", "gauge", "histogram")

#: schema-version literals all share this prefix (the artifact
#: namespace); anything matching it in a writer dict is a schema pin
_SCHEMA_PREFIX = "apex-tpu/"

#: a full versioned artifact id (``apex-tpu/<artifact>/v<n>``) — what a
#: schema CONSTANT must hold; bare-prefix strings (validator
#: ``startswith`` literals, this module's own namespace constant) are
#: not themselves schema pins
_SCHEMA_ID_RE = re.compile(r"^apex-tpu/[a-z0-9_.-]+/v\d+$")

#: a metric family name: dotted lowercase words (every real family has
#: at least one dot — ``serving.admitted``, ``pool.host_tier_demotes``)
_FAMILY_RE = re.compile(r"^[a-z_][a-z0-9_]*(?:\.[a-z0-9_]+)+$")

#: an event kind: one lowercase word, optionally dotted
#: (``fleet.alert``)
_EVENT_RE = re.compile(r"^[a-z_][a-z0-9_]*(?:\.[a-z0-9_]+)?$")


@dataclasses.dataclass(frozen=True)
class Site:
    """One source location a contract fact was extracted from."""
    path: str
    line: int
    col: int = 1
    end_line: int = 0
    scope: str = "<module>"


@dataclasses.dataclass(frozen=True)
class MetricSite:
    family: str
    kind: str                        # counter | gauge | histogram
    label_keys: FrozenSet[str]       # statically resolved literal keys
    opaque_labels: bool              # a non-literal labels expr (or
    site: Site = None                # ``**spread``) contributes keys
    #                                  we cannot see


@dataclasses.dataclass(frozen=True)
class RouteSite:
    route: str                       # "/v1/generate" or "/v1/cancel/"
    prefix: bool                     # True for ``path.startswith`` routes
    site: Site = None


@dataclasses.dataclass
class SchemaConst:
    name: str                        # REPORT_SCHEMA
    value: str                       # "apex-tpu/scenario-report/v1"
    site: Site = None
    stamped: bool = False            # a writer dict carries it
    validated: bool = False          # a reader compares against it


@dataclasses.dataclass
class StrTupleConst:
    """A module-level tuple-of-strings constant (field pins, ledger
    extraction tuples, gating classes) with one site per element."""
    module: str
    name: str
    values: Tuple[str, ...]
    site: Site = None
    element_sites: Tuple[Site, ...] = ()


@dataclasses.dataclass
class ContractIndex:
    metrics: List[MetricSite] = dataclasses.field(default_factory=list)
    unresolved_metrics: List[Tuple[Site, str]] = \
        dataclasses.field(default_factory=list)
    event_emits: Dict[str, List[Site]] = \
        dataclasses.field(default_factory=dict)
    event_consumers: Dict[str, List[Site]] = \
        dataclasses.field(default_factory=dict)
    routes: List[RouteSite] = dataclasses.field(default_factory=list)
    client_paths: List[Tuple[str, Site]] = \
        dataclasses.field(default_factory=list)
    sse_emits: Dict[str, List[Site]] = \
        dataclasses.field(default_factory=dict)
    sse_parses: Dict[str, List[Site]] = \
        dataclasses.field(default_factory=dict)
    schemas: List[SchemaConst] = dataclasses.field(default_factory=list)
    raw_schema_stamps: List[Tuple[str, Site]] = \
        dataclasses.field(default_factory=list)
    str_tuples: Dict[Tuple[str, str], StrTupleConst] = \
        dataclasses.field(default_factory=dict)
    # -- text consumers ----------------------------------------------------
    doc_metrics: Dict[str, Site] = dataclasses.field(default_factory=dict)
    doc_events: Dict[str, Site] = dataclasses.field(default_factory=dict)
    doc_routes: Dict[str, Site] = dataclasses.field(default_factory=dict)
    has_doc_metrics: bool = False    # the catalog section exists at all
    has_doc_events: bool = False
    has_doc_routes: bool = False
    golden_families: Dict[str, Site] = \
        dataclasses.field(default_factory=dict)

    def produced_families(self) -> Dict[str, List[MetricSite]]:
        out: Dict[str, List[MetricSite]] = {}
        for m in self.metrics:
            out.setdefault(m.family, []).append(m)
        return out

    def tuple_by_name(self, name: str) -> Optional[StrTupleConst]:
        """The unique tuple constant with this name, if exactly one
        module defines it (the pin/ledger names are repo-unique)."""
        hits = [t for (_, n), t in self.str_tuples.items() if n == name]
        return hits[0] if len(hits) == 1 else None


def _module_dotted(path: str) -> str:
    mod = path[:-3] if path.endswith(".py") else path
    mod = mod.replace("/", ".")
    if mod.endswith(".__init__"):
        mod = mod[: -len(".__init__")]
    return mod


def _site(mi: ModuleIndex, node: ast.AST) -> Site:
    return Site(path=mi.path, line=getattr(node, "lineno", 1),
                col=getattr(node, "col_offset", 0) + 1,
                end_line=getattr(node, "end_lineno", 0)
                or getattr(node, "lineno", 1),
                scope=mi.scope_of(node))


def _const_str_values(node: ast.AST) -> Optional[Tuple[str, ...]]:
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for e in node.elts:
            if isinstance(e, ast.Constant) and isinstance(e.value, str):
                out.append(e.value)
            else:
                return None
        return tuple(out)
    return None


class _ModuleConsts:
    """Module-level ``NAME = "str" | ("a", "b", ...)`` constants plus
    the ``from X import NAME`` table — the cross-module half of name
    resolution (``_RUN_COUNTERS`` lives in scheduler.py, the f-string
    that spends it in frontend.py)."""

    def __init__(self, modules: Dict[str, ModuleIndex]):
        self.strs: Dict[str, Dict[str, str]] = {}
        self.tuples: Dict[str, Dict[str, Tuple[str, ...]]] = {}
        self.imports: Dict[str, Dict[str, Tuple[str, str]]] = {}
        for rel, mi in modules.items():
            mod = _module_dotted(rel)
            self.strs[mod] = {}
            self.tuples[mod] = {}
            for node in mi.tree.body:
                if not (isinstance(node, ast.Assign)
                        and len(node.targets) == 1
                        and isinstance(node.targets[0], ast.Name)):
                    continue
                name = node.targets[0].id
                if isinstance(node.value, ast.Constant) \
                        and isinstance(node.value.value, str):
                    self.strs[mod][name] = node.value.value
                else:
                    vals = _const_str_values(node.value)
                    if vals is not None:
                        self.tuples[mod][name] = vals
            imp: Dict[str, Tuple[str, str]] = {}
            for entry in mi.imports:
                if entry.attr:
                    src = entry.module
                    if getattr(entry, "level", 0):
                        pkg = mod.rsplit(".", entry.level)[0] \
                            if "." in mod else mod
                        src = f"{pkg}.{entry.module}" \
                            if entry.module else pkg
                    imp[entry.local] = (src, entry.attr)
            self.imports[mod] = imp

    def lookup_tuple(self, module: str, name: str) \
            -> Optional[Tuple[str, ...]]:
        vals = self.tuples.get(module, {}).get(name)
        if vals is not None:
            return vals
        src = self.imports.get(module, {}).get(name)
        if src is not None:
            return self.tuples.get(src[0], {}).get(src[1])
        return None

    def lookup_str(self, module: str, name: str) -> Optional[str]:
        v = self.strs.get(module, {}).get(name)
        if v is not None:
            return v
        src = self.imports.get(module, {}).get(name)
        if src is not None:
            return self.strs.get(src[0], {}).get(src[1])
        return None


class _Resolver:
    """Static string resolution inside one function/comprehension
    context: literals, f-strings, loop variables over literal tuples,
    ``dict.items()`` loops over a local dict literal, and module/
    imported constants. ``resolve`` returns the full set of values an
    expression can take, or None when any part is dynamic."""

    def __init__(self, consts: _ModuleConsts, module: str):
        self.consts = consts
        self.module = module
        self.env: List[Dict[str, Tuple[str, ...]]] = []
        self.local_dicts: Dict[str, Tuple[str, ...]] = {}
        self.local_tuples: Dict[str, Tuple[str, ...]] = {}

    def push(self, binding: Dict[str, Tuple[str, ...]]) -> None:
        self.env.append(binding)

    def pop(self) -> None:
        self.env.pop()

    def _name_values(self, name: str) -> Optional[Tuple[str, ...]]:
        for frame in reversed(self.env):
            if name in frame:
                return frame[name]
        if name in self.local_tuples:
            # a local ``x = "lit"`` binds one value, not an iteration
            vals = self.local_tuples[name]
            if len(vals) == 1:
                return vals
            return None
        v = self.consts.lookup_str(self.module, name)
        return (v,) if v is not None else None

    def iter_values(self, node: ast.AST) -> Optional[Tuple[str, ...]]:
        """Values a ``for x in <node>`` loop binds, when literal."""
        vals = _const_str_values(node)
        if vals is not None:
            return vals
        if isinstance(node, ast.Name):
            for frame in reversed(self.env):
                if node.id in frame:
                    return frame[node.id]
            vals = self.local_tuples.get(node.id)
            if vals is not None:
                return vals
            return self.consts.lookup_tuple(self.module, node.id)
        if isinstance(node, ast.Call) \
                and isinstance(node.func, ast.Attribute) \
                and node.func.attr == "items" \
                and isinstance(node.func.value, ast.Name):
            return self.local_dicts.get(node.func.value.id)
        return None

    def resolve(self, node: ast.AST) -> Optional[Set[str]]:
        if isinstance(node, ast.Constant) \
                and isinstance(node.value, str):
            return {node.value}
        if isinstance(node, ast.Name):
            vals = self._name_values(node.id)
            return set(vals) if vals is not None else None
        if isinstance(node, ast.IfExp):
            a = self.resolve(node.body)
            b = self.resolve(node.orelse)
            return a | b if a is not None and b is not None else None
        if isinstance(node, ast.JoinedStr):
            parts: List[Set[str]] = []
            for part in node.values:
                if isinstance(part, ast.Constant):
                    parts.append({str(part.value)})
                elif isinstance(part, ast.FormattedValue):
                    if part.format_spec is not None:
                        return None
                    sub = self.resolve(part.value)
                    if sub is None:
                        return None
                    parts.append(sub)
                else:
                    return None
            out: Set[str] = {""}
            for p in parts:
                out = {a + b for a in out for b in p}
            return out
        return None


def _dict_literal_keys(node: ast.Dict) \
        -> Tuple[FrozenSet[str], bool]:
    keys: Set[str] = set()
    opaque = False
    for k in node.keys:
        if k is None:                      # ``**spread``
            opaque = True
        elif isinstance(k, ast.Constant) and isinstance(k.value, str):
            keys.add(k.value)
        else:
            opaque = True
    return frozenset(keys), opaque


class _ModuleExtractor(ast.NodeVisitor):
    """One pass over one module's tree, maintaining the loop-binding
    environment so names at call sites resolve in context."""

    def __init__(self, mi: ModuleIndex, consts: _ModuleConsts,
                 index: ContractIndex):
        self.mi = mi
        self.index = index
        self.resolver = _Resolver(consts, _module_dotted(mi.path))

    # -- scope bookkeeping -------------------------------------------------

    def _prescan_function(self, node: ast.AST) -> Tuple[dict, dict]:
        """Function-local ``x = {...literal...}`` / ``x = (...)``
        assignments, so ``for name, v in vals.items():`` and
        ``labels=lbl`` resolve."""
        dicts: Dict[str, Tuple[str, ...]] = {}
        tuples: Dict[str, Tuple[str, ...]] = {}
        for sub in ast.walk(node):
            if not (isinstance(sub, ast.Assign)
                    and len(sub.targets) == 1
                    and isinstance(sub.targets[0], ast.Name)):
                continue
            tname = sub.targets[0].id
            if isinstance(sub.value, ast.Dict):
                keys = []
                for k in sub.value.keys:
                    if isinstance(k, ast.Constant) \
                            and isinstance(k.value, str):
                        keys.append(k.value)
                    else:
                        keys = None
                        break
                if keys is not None:
                    dicts[tname] = tuple(keys)
            else:
                vals = _const_str_values(sub.value)
                if vals is not None:
                    tuples[tname] = vals
                elif isinstance(sub.value, ast.Constant) \
                        and isinstance(sub.value.value, str):
                    tuples[tname] = (sub.value.value,)
        return dicts, tuples

    def visit_FunctionDef(self, node):
        saved = (self.resolver.local_dicts, self.resolver.local_tuples)
        d, t = self._prescan_function(node)
        self.resolver.local_dicts = d
        self.resolver.local_tuples = t
        self.generic_visit(node)
        self.resolver.local_dicts, self.resolver.local_tuples = saved

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_For(self, node: ast.For):
        binding: Dict[str, Tuple[str, ...]] = {}
        vals = self.resolver.iter_values(node.iter)
        if vals is not None:
            if isinstance(node.target, ast.Name):
                binding[node.target.id] = vals
            elif isinstance(node.target, ast.Tuple) \
                    and node.target.elts \
                    and isinstance(node.target.elts[0], ast.Name):
                # ``for name, v in vals.items()`` — keys bind first
                binding[node.target.elts[0].id] = vals
        self.resolver.push(binding)
        self.generic_visit(node)
        self.resolver.pop()

    def _visit_comprehension(self, node):
        binding: Dict[str, Tuple[str, ...]] = {}
        for gen in node.generators:
            vals = self.resolver.iter_values(gen.iter)
            if vals is not None and isinstance(gen.target, ast.Name):
                binding[gen.target.id] = vals
        self.resolver.push(binding)
        self.generic_visit(node)
        self.resolver.pop()

    visit_ListComp = _visit_comprehension
    visit_SetComp = _visit_comprehension
    visit_DictComp = _visit_comprehension
    visit_GeneratorExp = _visit_comprehension

    # -- module-level constants --------------------------------------------

    def visit_Assign(self, node: ast.Assign):
        if len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and self.mi.scope_of(node) == "<module>":
            name = node.targets[0].id
            if isinstance(node.value, ast.Constant) \
                    and isinstance(node.value.value, str) \
                    and _SCHEMA_ID_RE.match(node.value.value):
                self.index.schemas.append(SchemaConst(
                    name=name, value=node.value.value,
                    site=_site(self.mi, node)))
            vals = _const_str_values(node.value)
            if vals is not None and isinstance(node.value,
                                               (ast.Tuple, ast.List)):
                self.index.str_tuples[
                    (_module_dotted(self.mi.path), name)] = \
                    StrTupleConst(
                        module=_module_dotted(self.mi.path), name=name,
                        values=vals, site=_site(self.mi, node),
                        element_sites=tuple(_site(self.mi, e)
                                            for e in node.value.elts))
        # ``x["schema"] = CONST`` writer stamps
        if len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Subscript):
            sub = node.targets[0]
            if isinstance(sub.slice, ast.Constant) \
                    and sub.slice.value == "schema":
                self._record_schema_stamp(node.value, node)
        self.generic_visit(node)

    # -- the call-site facts -----------------------------------------------

    def visit_Call(self, node: ast.Call):
        func = node.func
        if isinstance(func, ast.Attribute):
            if func.attr in _INSTRUMENT_KINDS \
                    and name_tail(func.value) == "metrics" and node.args:
                self._record_metric(node, func.attr)
            elif func.attr == "emit" \
                    and name_tail(func.value) == "events" and node.args:
                self._record_emit(node)
            elif func.attr == "_sse" and len(node.args) >= 2:
                kinds = self.resolver.resolve(node.args[1])
                if kinds:
                    for kind in kinds:
                        self.index.sse_emits.setdefault(
                            kind, []).append(_site(self.mi, node))
            elif func.attr == "startswith" and node.args:
                lit = node.args[0]
                if isinstance(lit, ast.Constant) \
                        and isinstance(lit.value, str):
                    if lit.value.startswith(_SCHEMA_PREFIX):
                        # prefix validator (the ledger's scenarios
                        # reader): validates every schema const whose
                        # value it prefixes
                        for sc in self.index.schemas:
                            if sc.value.startswith(lit.value):
                                sc.validated = True
                        self._pending_schema_prefixes.append(lit.value)
                    elif name_tail(func.value) == "path" \
                            and lit.value.startswith("/"):
                        self.index.routes.append(RouteSite(
                            route=lit.value, prefix=True,
                            site=_site(self.mi, node)))
            elif func.attr == "_get_json" and node.args:
                self._record_client_path(node.args[0], node)
        self.generic_visit(node)

    def visit_Dict(self, node: ast.Dict):
        # ``{"schema": X, ...}`` writer stamps
        for k, v in zip(node.keys, node.values):
            if isinstance(k, ast.Constant) and k.value == "schema":
                self._record_schema_stamp(v, node)
        self.generic_visit(node)

    def visit_Compare(self, node: ast.Compare):
        self._record_kind_compare(node)
        self._record_route_compare(node)
        self._record_sse_parse(node)
        self._record_schema_compare(node)
        self.generic_visit(node)

    def visit_JoinedStr(self, node: ast.JoinedStr):
        self._record_request_head(node)
        self.generic_visit(node)

    def visit_Constant(self, node: ast.Constant):
        if isinstance(node.value, str):
            self._record_request_head(node)

    # -- recorders ---------------------------------------------------------

    def _record_metric(self, node: ast.Call, kind: str) -> None:
        families = self.resolver.resolve(node.args[0])
        site = _site(self.mi, node)
        if not families:
            self.index.unresolved_metrics.append(
                (site, ast.unparse(node.args[0])[:60]))
            return
        label_keys: FrozenSet[str] = frozenset()
        opaque = False
        labels = next((kw.value for kw in node.keywords
                       if kw.arg == "labels"), None)
        if labels is not None:
            if isinstance(labels, ast.Dict):
                label_keys, opaque = _dict_literal_keys(labels)
            elif isinstance(labels, ast.Name) \
                    and labels.id in self.resolver.local_dicts:
                label_keys = frozenset(
                    self.resolver.local_dicts[labels.id])
            else:
                opaque = True
        for family in sorted(families):
            self.index.metrics.append(MetricSite(
                family=family, kind=kind, label_keys=label_keys,
                opaque_labels=opaque, site=site))

    def _record_emit(self, node: ast.Call) -> None:
        kinds = self.resolver.resolve(node.args[0])
        site = _site(self.mi, node)
        if not kinds:
            return
        for kind in sorted(kinds):
            self.index.event_emits.setdefault(kind, []).append(site)

    def _record_kind_compare(self, node: ast.Compare) -> None:
        """``e["kind"] == "lit"`` / ``e.get("kind") == "lit"`` /
        ``e["kind"] in ("a", "b")`` — dict-shaped event reads only."""
        def is_kind_read(expr: ast.AST) -> bool:
            if isinstance(expr, ast.Subscript) \
                    and isinstance(expr.slice, ast.Constant):
                return expr.slice.value == "kind"
            if isinstance(expr, ast.Call) \
                    and isinstance(expr.func, ast.Attribute) \
                    and expr.func.attr == "get" and expr.args:
                a0 = expr.args[0]
                return isinstance(a0, ast.Constant) \
                    and a0.value == "kind"
            return False

        sides = [node.left] + list(node.comparators)
        if not any(is_kind_read(s) for s in sides):
            return
        site = _site(self.mi, node)
        for s in sides:
            if isinstance(s, ast.Constant) and isinstance(s.value, str):
                self.index.event_consumers.setdefault(
                    s.value, []).append(site)
            else:
                for v in _const_str_values(s) or ():
                    self.index.event_consumers.setdefault(
                        v, []).append(site)

    def _record_route_compare(self, node: ast.Compare) -> None:
        """``path == "/x"`` / ``path in ("/x", "/")`` route dispatch."""
        if not (isinstance(node.left, ast.Name)
                and node.left.id == "path"):
            return
        site = _site(self.mi, node)
        for comp in node.comparators:
            if isinstance(comp, ast.Constant) \
                    and isinstance(comp.value, str) \
                    and comp.value.startswith("/"):
                self.index.routes.append(RouteSite(
                    route=comp.value, prefix=False, site=site))
            else:
                for v in _const_str_values(comp) or ():
                    if v.startswith("/"):
                        self.index.routes.append(RouteSite(
                            route=v, prefix=False, site=site))

    def _record_sse_parse(self, node: ast.Compare) -> None:
        if not (isinstance(node.left, ast.Name)
                and node.left.id == "event"):
            return
        site = _site(self.mi, node)
        for comp in node.comparators:
            if isinstance(comp, ast.Constant) \
                    and isinstance(comp.value, str):
                self.index.sse_parses.setdefault(
                    comp.value, []).append(site)

    def _record_schema_compare(self, node: ast.Compare) -> None:
        names = set()
        for s in [node.left] + list(node.comparators):
            tail = name_tail(s)
            if tail:
                names.add(tail)
        for sc in self.index.schemas:
            if sc.name in names:
                sc.validated = True
        self._pending_schema_names.update(names)

    def _record_schema_stamp(self, value: ast.AST,
                             node: ast.AST) -> None:
        tail = name_tail(value)
        if tail is not None:
            for sc in self.index.schemas:
                if sc.name == tail:
                    sc.stamped = True
            self._pending_stamp_names.add(tail)
        elif isinstance(value, ast.Constant) \
                and isinstance(value.value, str) \
                and value.value.startswith(_SCHEMA_PREFIX):
            self.index.raw_schema_stamps.append(
                (value.value, _site(self.mi, node)))

    _REQUEST_HEAD = re.compile(
        r"^(?:GET|POST|PUT|DELETE|HEAD) (/[^\s{?]*)")

    def _record_request_head(self, node: ast.AST) -> None:
        """Raw request lines (``f"POST /v1/generate HTTP/1.1..."``):
        the literal path prefix before any query/format field."""
        if isinstance(node, ast.JoinedStr):
            first = node.values[0] if node.values else None
            text = first.value \
                if isinstance(first, ast.Constant) else None
        else:
            text = node.value
        if not isinstance(text, str):
            return
        m = self._REQUEST_HEAD.match(text)
        if m and m.group(1):
            self._record_client_literal(m.group(1),
                                        _site(self.mi, node))

    def _record_client_path(self, arg: ast.AST, node: ast.AST) -> None:
        text: Optional[str] = None
        if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
            text = arg.value
        elif isinstance(arg, ast.JoinedStr) and arg.values \
                and isinstance(arg.values[0], ast.Constant):
            text = str(arg.values[0].value)
        if text and text.startswith("/"):
            self._record_client_literal(text, _site(self.mi, node))

    def _record_client_literal(self, text: str, site: Site) -> None:
        path = text.split("?", 1)[0]
        self.index.client_paths.append((path, site))

    def run(self) -> None:
        self._pending_schema_names: Set[str] = set()
        self._pending_stamp_names: Set[str] = set()
        self._pending_schema_prefixes: List[str] = []
        self.visit(self.mi.tree)


# --------------------------------------------------------------------------
# text surfaces
# --------------------------------------------------------------------------

_BACKTICK = re.compile(r"`([^`]+)`")
_DOC_ROUTE = re.compile(
    r"^(?:GET|POST|PUT|DELETE|HEAD)\s+(/\S*)")
_PROM_TYPE = re.compile(
    r"^# TYPE ([a-zA-Z_:][a-zA-Z0-9_:]*) (?:counter|gauge|histogram)")
_HEADING = re.compile(r"^#{1,6}\s+(.*)$")


def _table_first_cells(lines: Sequence[str], start: int,
                       path: str) -> List[Tuple[str, Site]]:
    """First-column cells of the markdown table(s) inside one section
    (rows start with ``|``; header + ``---`` separator rows skipped)."""
    out: List[Tuple[str, Site]] = []
    for i in range(start, len(lines)):
        line = lines[i]
        if _HEADING.match(line):
            break
        stripped = line.strip()
        if not stripped.startswith("|"):
            continue
        cells = [c.strip() for c in stripped.strip("|").split("|")]
        if not cells or set(cells[0]) <= {"-", ":", " "}:
            continue
        out.append((cells[0], Site(path=path, line=i + 1)))
    return out


def parse_doc_catalogs(path: str, text: str,
                       index: ContractIndex) -> None:
    """``docs/observability.md``: the "Instrument catalog" and "Event
    catalog" tables. Only the catalog sections count — prose mentions
    of a family elsewhere are narrative, not contract."""
    lines = text.splitlines()
    for i, line in enumerate(lines):
        m = _HEADING.match(line)
        if not m:
            continue
        title = m.group(1).strip().lower()
        if "instrument catalog" in title:
            index.has_doc_metrics = True
            for cell, site in _table_first_cells(lines, i + 1, path):
                for tok in _BACKTICK.findall(cell):
                    if _FAMILY_RE.match(tok):
                        index.doc_metrics.setdefault(tok, site)
        elif "event catalog" in title:
            index.has_doc_events = True
            for cell, site in _table_first_cells(lines, i + 1, path):
                for tok in _BACKTICK.findall(cell):
                    if _EVENT_RE.match(tok):
                        index.doc_events.setdefault(tok, site)


def parse_doc_routes(path: str, text: str,
                     index: ContractIndex) -> None:
    """``docs/http.md``: the endpoint table — ``| `GET /path` | ... |``
    rows. ``<placeholder>`` suffixes and query strings are stripped so
    ``/v1/cancel/<request_id>`` matches the ``startswith`` dispatch."""
    lines = text.splitlines()
    for i, line in enumerate(lines):
        m = _HEADING.match(line)
        if not m or "endpoint" not in m.group(1).strip().lower():
            continue
        index.has_doc_routes = True
        for cell, site in _table_first_cells(lines, i + 1, path):
            for tok in _BACKTICK.findall(cell):
                rm = _DOC_ROUTE.match(tok)
                if not rm:
                    continue
                route = rm.group(1).split("?", 1)[0]
                cut = route.find("<")
                if cut >= 0:
                    route = route[:cut]
                index.doc_routes.setdefault(route, site)


def parse_golden_prom(path: str, text: str,
                      index: ContractIndex) -> None:
    for i, line in enumerate(text.splitlines()):
        m = _PROM_TYPE.match(line)
        if m:
            index.golden_families.setdefault(
                m.group(1), Site(path=path, line=i + 1))


# --------------------------------------------------------------------------
# entry point
# --------------------------------------------------------------------------

def build_index(modules: Dict[str, ModuleIndex],
                texts: Dict[str, str]) -> ContractIndex:
    """The whole contract index: python facts from the pre-parsed
    module map, text facts from the doc/golden surface (``texts`` maps
    rel path -> contents for the non-python files)."""
    index = ContractIndex()
    consts = _ModuleConsts(modules)
    extractors = []
    for rel in sorted(modules):
        ex = _ModuleExtractor(modules[rel], consts, index)
        extractors.append(ex)
        ex.run()
    # cross-module schema stamps/validators: a constant defined in one
    # module may be stamped or compared in another (``report.
    # SCENARIOS_SCHEMA`` in scenarios/__main__.py), and module visit
    # order must not matter
    stamp_names: Set[str] = set()
    compare_names: Set[str] = set()
    prefixes: List[str] = []
    for ex in extractors:
        stamp_names |= ex._pending_stamp_names
        compare_names |= ex._pending_schema_names
        prefixes.extend(ex._pending_schema_prefixes)
    for sc in index.schemas:
        if sc.name in stamp_names:
            sc.stamped = True
        if sc.name in compare_names \
                or any(sc.value.startswith(p) for p in prefixes):
            sc.validated = True
    for rel in sorted(texts):
        text = texts[rel]
        base = rel.rsplit("/", 1)[-1]
        if rel.endswith(".prom"):
            parse_golden_prom(rel, text, index)
        elif base == "http.md":
            parse_doc_routes(rel, text, index)
        elif base.endswith(".md"):
            parse_doc_catalogs(rel, text, index)
    return index
