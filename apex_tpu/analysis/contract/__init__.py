"""tpu-lint tier 5: wire & observability contract analysis.

The stack's remaining un-proved surface is string-keyed: metric
families, event kinds, HTTP routes, SSE frame kinds, ``apex-tpu/*``
schema pins, report field pins, ledger gating classes. Producers and
consumers of those names live in different files (and two of the
consumers are not even python — the docs catalogs and the golden
Prometheus exposition), so no per-module check can see them drift.
This tier builds one repo-wide :class:`~apex_tpu.analysis.contract.
extract.ContractIndex` and proves both directions of each contract
with the ``contract-*`` rules — stdlib ``ast`` plus text parsing, no
TPU, no network, same CLI/suppression/baseline/diff conventions as
tiers 1–4 (``python -m apex_tpu.analysis --contract``).
"""

from apex_tpu.analysis.contract.contract_rules import (CONTRACT_RULES,
                                                       ContractRule)
from apex_tpu.analysis.contract.contract_report import (
    TEXT_SURFACE, TextSuppressions, analyze_contract,
    analyze_contract_sources, build_contract_index, read_text_surface,
    split_surface)
from apex_tpu.analysis.contract.extract import ContractIndex, build_index

__all__ = [
    "CONTRACT_RULES", "ContractRule", "ContractIndex", "TEXT_SURFACE",
    "TextSuppressions", "analyze_contract", "analyze_contract_sources",
    "build_contract_index", "build_index", "read_text_surface",
    "split_surface",
]
