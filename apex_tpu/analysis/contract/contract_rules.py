"""tpu-lint contract rules: producer/consumer drift proofs for the wire.

Every rule here checks one direction of one string-keyed contract
against the shared :class:`~apex_tpu.analysis.contract.extract.
ContractIndex`: instrument families vs the docs catalog and the golden
exposition, event kinds vs their readers, HTTP routes and SSE frames vs
both sides of the socket, ``apex-tpu/*`` schema pins vs their writers
and validators, and the perf ledger's extraction tuples vs the report
pins and gating classes. The bias matches the other tiers: a rule
speaks only where the index holds a statically resolved fact, and the
repo's intentional gaps are inline-suppressed at the fact's site with a
justification — the baseline ships (and stays) empty.

Rename detection: when a produced family is missing from the docs AND a
near-identical doc entry has no producer, the pair is reported as ONE
``contract-undocumented-metric`` finding naming both sides ("renamed
without updating the catalog?") instead of an undocumented+stale double
hit — drift reports should describe the edit that caused them.
"""

from __future__ import annotations

import dataclasses
import difflib
from typing import Callable, Dict, Iterator, List, Optional, Tuple

from apex_tpu.analysis.contract.extract import (ContractIndex, MetricSite,
                                                Site)
from apex_tpu.analysis.walker import Finding


@dataclasses.dataclass(frozen=True)
class ContractRule:
    name: str
    severity: str
    summary: str
    check: Callable              # check(index: ContractIndex) -> Iterator


CONTRACT_RULES: Dict[str, ContractRule] = {}


def contract_rule(name: str, severity: str, summary: str):
    def deco(fn):
        CONTRACT_RULES[name] = ContractRule(
            name=name, severity=severity, summary=summary, check=fn)
        return fn
    return deco


def _finding(rule: ContractRule, site: Site, message: str) -> Finding:
    return Finding(rule=rule.name, severity=rule.severity,
                   path=site.path, line=site.line, col=site.col,
                   message=message, scope=site.scope,
                   end_line=site.end_line or site.line)


def _first(sites: List[Site]) -> Site:
    return min(sites, key=lambda s: (s.path, s.line, s.col))


def _rename_pairs(index: ContractIndex) -> Dict[str, str]:
    """undocumented-produced-family -> stale-doc-only-family pairs that
    look like a rename (one edit, reported once)."""
    produced = set(index.produced_families())
    undocumented = sorted(produced - set(index.doc_metrics))
    stale = sorted(set(index.doc_metrics) - produced)
    pairs: Dict[str, str] = {}
    taken: set = set()
    for fam in undocumented:
        hit = difflib.get_close_matches(
            fam, [s for s in stale if s not in taken], n=1, cutoff=0.8)
        if hit:
            pairs[fam] = hit[0]
            taken.add(hit[0])
    return pairs


# --------------------------------------------------------------------------
# 1. contract-undocumented-metric
# --------------------------------------------------------------------------

@contract_rule("contract-undocumented-metric", "error",
               "a registered metric family is missing from the docs "
               "instrument catalog (or its name is not statically "
               "resolvable at the registration site)")
def check_undocumented_metric(index: ContractIndex) -> Iterator[Finding]:
    r = CONTRACT_RULES["contract-undocumented-metric"]
    for site, expr in index.unresolved_metrics:
        yield _finding(
            r, site,
            f"metric name `{expr}` is not statically resolvable — the "
            "instrument catalog cannot be checked against it; register "
            "with a literal, a literal tuple loop, or a module "
            "constant")
    if not index.has_doc_metrics:
        return
    pairs = _rename_pairs(index)
    produced = index.produced_families()
    for family in sorted(set(produced) - set(index.doc_metrics)):
        site = _first([m.site for m in produced[family]])
        old = pairs.get(family)
        if old:
            yield _finding(
                r, site,
                f"metric family `{family}` is registered here but the "
                f"docs instrument catalog lists `{old}` — renamed "
                "without updating the catalog?")
        else:
            yield _finding(
                r, site,
                f"metric family `{family}` is registered here but "
                "missing from the docs instrument catalog "
                "(docs/observability.md)")


# --------------------------------------------------------------------------
# 2. contract-stale-doc-metric
# --------------------------------------------------------------------------

@contract_rule("contract-stale-doc-metric", "error",
               "the docs instrument catalog lists a metric family no "
               "code registers")
def check_stale_doc_metric(index: ContractIndex) -> Iterator[Finding]:
    r = CONTRACT_RULES["contract-stale-doc-metric"]
    if not (index.metrics or index.unresolved_metrics):
        return          # no python producer surface scanned at all
    produced = set(index.produced_families())
    renamed_to = set(_rename_pairs(index).values())
    for family in sorted(set(index.doc_metrics) - produced):
        if family in renamed_to:
            continue     # reported once, as the rename, by rule 1
        yield _finding(
            r, index.doc_metrics[family],
            f"instrument catalog lists `{family}` but no code "
            "registers that family")


# --------------------------------------------------------------------------
# 3. contract-label-drift
# --------------------------------------------------------------------------

@contract_rule("contract-label-drift", "error",
               "one metric family is registered with conflicting "
               "label-key sets or conflicting instrument kinds across "
               "sites")
def check_label_drift(index: ContractIndex) -> Iterator[Finding]:
    r = CONTRACT_RULES["contract-label-drift"]
    for family, sites in sorted(index.produced_families().items()):
        by_kind: Dict[str, MetricSite] = {}
        for m in sites:
            by_kind.setdefault(m.kind, m)
        if len(by_kind) > 1:
            kinds = sorted(by_kind)
            second = by_kind[kinds[1]]
            first = by_kind[kinds[0]]
            yield _finding(
                r, second.site,
                f"family `{family}` is registered as a "
                f"{kinds[1]} here but as a {kinds[0]} at "
                f"{first.site.path}:{first.site.line} — one family, "
                "one instrument kind")
        # label-key comparison only between sites whose keys fully
        # resolved; an opaque ``labels=<expr>`` site proves nothing
        seen: Dict[frozenset, MetricSite] = {}
        for m in sites:
            if m.opaque_labels:
                continue
            if m.label_keys not in seen:
                if seen:
                    other = next(iter(seen.values()))
                    yield _finding(
                        r, m.site,
                        f"family `{family}` is registered with label "
                        f"keys {sorted(m.label_keys)} here but "
                        f"{sorted(other.label_keys)} at "
                        f"{other.site.path}:{other.site.line} — "
                        "label sets must agree per family")
                seen[m.label_keys] = m


# --------------------------------------------------------------------------
# 4 / 5. events: orphans and dead consumers
# --------------------------------------------------------------------------

@contract_rule("contract-orphan-event", "error",
               "an emitted event kind has no docs catalog entry and no "
               "code consumer — nobody can be relying on it, or "
               "somebody is and it is invisible")
def check_orphan_event(index: ContractIndex) -> Iterator[Finding]:
    r = CONTRACT_RULES["contract-orphan-event"]
    for kind in sorted(index.event_emits):
        if kind in index.doc_events or kind in index.event_consumers:
            continue
        yield _finding(
            r, _first(index.event_emits[kind]),
            f"event kind `{kind}` is emitted here but appears in no "
            "docs event catalog and no code reads it")


@contract_rule("contract-dead-event-consumer", "error",
               "a docs-cataloged or code-consumed event kind has no "
               "emitter")
def check_dead_event_consumer(index: ContractIndex) -> Iterator[Finding]:
    r = CONTRACT_RULES["contract-dead-event-consumer"]
    for kind in sorted(index.event_consumers):
        if kind in index.event_emits:
            continue
        yield _finding(
            r, _first(index.event_consumers[kind]),
            f"this code filters on event kind `{kind}` but nothing "
            "emits it")
    for kind in sorted(index.doc_events):
        if kind in index.event_emits:
            continue
        yield _finding(
            r, index.doc_events[kind],
            f"docs event catalog lists `{kind}` but nothing emits it")


# --------------------------------------------------------------------------
# 6. contract-schema-unpinned
# --------------------------------------------------------------------------

@contract_rule("contract-schema-unpinned", "error",
               "an apex-tpu/* schema literal lacks its writer stamp or "
               "its paired validator, or a writer stamps a raw string "
               "instead of a named constant")
def check_schema_unpinned(index: ContractIndex) -> Iterator[Finding]:
    r = CONTRACT_RULES["contract-schema-unpinned"]
    for value, site in sorted(index.raw_schema_stamps,
                              key=lambda vs: (vs[1].path, vs[1].line)):
        yield _finding(
            r, site,
            f"writer stamps the raw schema literal `{value}` — promote "
            "it to a named module constant so validators can pin it")
    for sc in sorted(index.schemas, key=lambda s: (s.site.path,
                                                   s.site.line)):
        if not sc.stamped:
            yield _finding(
                r, sc.site,
                f"schema constant `{sc.name}` = \"{sc.value}\" is "
                "never stamped into a written document "
                "(`\"schema\": ...` key)")
        if not sc.validated:
            yield _finding(
                r, sc.site,
                f"schema constant `{sc.name}` = \"{sc.value}\" has no "
                "paired validator (no comparison or prefix check reads "
                "it back)")


# --------------------------------------------------------------------------
# 7. contract-endpoint-undocumented
# --------------------------------------------------------------------------

def _served_by(path: str, index: ContractIndex) -> bool:
    for rt in index.routes:
        if (rt.prefix and path.startswith(rt.route)) \
                or (not rt.prefix and path == rt.route):
            return True
    return False


@contract_rule("contract-endpoint-undocumented", "error",
               "HTTP routes vs the docs endpoint table (both ways), "
               "client request paths vs served routes, and SSE frame "
               "kinds vs the client parsers (both ways)")
def check_endpoint_undocumented(index: ContractIndex) -> Iterator[Finding]:
    r = CONTRACT_RULES["contract-endpoint-undocumented"]
    if index.has_doc_routes:
        reported = set()
        for rt in sorted(index.routes,
                         key=lambda x: (x.route, x.site.path,
                                        x.site.line)):
            if rt.route in reported:
                continue
            documented = rt.route in index.doc_routes or (
                rt.prefix and any(d.startswith(rt.route)
                                  for d in index.doc_routes))
            if not documented:
                reported.add(rt.route)
                yield _finding(
                    r, rt.site,
                    f"route `{rt.route}` is served here but missing "
                    "from the docs endpoint table (docs/http.md)")
        for doc_route in sorted(index.doc_routes):
            if not _served_by(doc_route, index):
                yield _finding(
                    r, index.doc_routes[doc_route],
                    f"docs endpoint table lists `{doc_route}` but no "
                    "dispatch serves it")
    if index.routes:
        for path, site in sorted(index.client_paths,
                                 key=lambda ps: (ps[0], ps[1].path,
                                                 ps[1].line)):
            if not _served_by(path, index):
                yield _finding(
                    r, site,
                    f"client requests `{path}` but no server dispatch "
                    "serves that path")
    parsed = set(index.sse_parses)
    for kind in sorted(index.sse_emits):
        if kind not in parsed:
            yield _finding(
                r, _first(index.sse_emits[kind]),
                f"SSE frame kind `{kind}` is emitted here but no "
                "client parse arm handles it")
    for kind in sorted(parsed - set(index.sse_emits)):
        if index.sse_emits:
            yield _finding(
                r, _first(index.sse_parses[kind]),
                f"client parses SSE frame kind `{kind}` but the "
                "server never emits it")


# --------------------------------------------------------------------------
# 8. contract-ledger-class-drift
# --------------------------------------------------------------------------

#: ledger extraction tuple -> (report pin tuple, banked-name prefix);
#: the ledger flattens ``scenario.<name>.<prefix><field>``
_EXTRACTION_PINS: Tuple[Tuple[str, str, str], ...] = (
    ("_SCENARIO_FIELDS", "AGGREGATE_FIELDS", ""),
    ("_SCENARIO_ROUTER_FIELDS", "ROUTER_FIELDS", ""),
    ("_SCENARIO_HOST_TIER_FIELDS", "HOST_TIER_FIELDS", ""),
    ("_SCENARIO_FLEET_FIELDS", "FLEET_FIELDS", "fleet_"),
    ("_SCENARIO_HTTP_FIELDS", "HTTP_FIELDS", "http_"),
)


def _gating_class(name: str, hb: Tuple[str, ...], lb: Tuple[str, ...],
                  rates: Tuple[str, ...]) -> Optional[str]:
    """Mirror of ``obs.ledger.check``'s classification: cost metrics
    gate exactly, direction-classified metrics band-gate (absolute for
    rate suffixes), anything else is silently informational."""
    if name.startswith("cost."):
        return "exact"
    if any(s in name for s in hb) or any(s in name for s in lb):
        if any(name.endswith(s) for s in rates):
            return "absolute-rate"
        return "relative-band"
    return None


def _element_site(tup, i: int) -> Site:
    if i < len(tup.element_sites):
        return tup.element_sites[i]
    return tup.site


@contract_rule("contract-ledger-class-drift", "error",
               "a ledger extraction field matches no gating class "
               "(silently informational) or is absent from the report "
               "pin it extracts from")
def check_ledger_class_drift(index: ContractIndex) -> Iterator[Finding]:
    r = CONTRACT_RULES["contract-ledger-class-drift"]
    hb_t = index.tuple_by_name("_HIGHER_BETTER")
    lb_t = index.tuple_by_name("_LOWER_BETTER")
    if hb_t is None or lb_t is None:
        return               # no ledger surface scanned
    rates_t = index.tuple_by_name("_RATE_SUFFIXES")
    hb, lb = hb_t.values, lb_t.values
    rates = rates_t.values if rates_t else ()
    for ext_name, pin_name, prefix in _EXTRACTION_PINS:
        ext = index.tuple_by_name(ext_name)
        if ext is None:
            continue
        pin = index.tuple_by_name(pin_name)
        for i, field in enumerate(ext.values):
            site = _element_site(ext, i)
            if pin is not None and field not in pin.values:
                yield _finding(
                    r, site,
                    f"`{ext_name}` extracts `{field}` but the report "
                    f"pin `{pin_name}` does not produce that key")
            if _gating_class(prefix + field, hb, lb, rates) is None:
                yield _finding(
                    r, site,
                    f"banked metric `scenario.<name>.{prefix}{field}` "
                    "matches no gating class (no direction substring, "
                    "no rate suffix) — the ledger records it but never "
                    "gates it")
    bench = index.tuple_by_name("_BENCH_FIELDS")
    if bench is not None:
        for i, field in enumerate(bench.values):
            if _gating_class(field, hb, lb, rates) is None:
                yield _finding(
                    r, _element_site(bench, i),
                    f"banked bench field `{field}` matches no gating "
                    "class (no direction substring, no rate suffix) — "
                    "the ledger records it but never gates it")


# --------------------------------------------------------------------------
# 9. contract-golden-stale
# --------------------------------------------------------------------------

_RAW_SERIES_SUFFIXES = ("_count", "_mean", "_last")


@contract_rule("contract-golden-stale", "error",
               "the golden Prometheus exposition pins a family no "
               "registered instrument produces")
def check_golden_stale(index: ContractIndex) -> Iterator[Finding]:
    r = CONTRACT_RULES["contract-golden-stale"]
    if not index.golden_families:
        return
    produced = {f.replace(".", "_") for f in index.produced_families()}
    for fam in sorted(index.golden_families):
        candidates = {fam}
        for suf in _RAW_SERIES_SUFFIXES:
            if fam.endswith(suf):
                candidates.add(fam[: -len(suf)])
        if not candidates & produced:
            yield _finding(
                r, index.golden_families[fam],
                f"golden exposition pins family `{fam}` but no "
                "registered instrument produces it (after the "
                "dots-to-underscores Prometheus mapping)")
