"""Orchestration for the tpu-lint contract tier.

:func:`analyze_contract_sources` is the engine: split the scanned
surface into its python half (parsed with the same ``parse_sources``
the other tiers use) and its text half (the docs catalogs and the
golden exposition), build the :class:`~apex_tpu.analysis.contract.
extract.ContractIndex`, run the selected ``contract-*`` rules, and
apply inline suppressions — the ordinary tokenize-based pragmas for
``.py`` files, a line-regex variant (:class:`TextSuppressions`) for the
markdown/prom files tokenize cannot read. Purely syntactic (stdlib
``ast`` + text, no jax import), so ``--diff`` can run it against a git
base rev's sources like the AST and conc tiers.

:func:`analyze_contract` is the disk-backed wrapper the CLI uses: the
same default python surface as every other tier, plus the fixed
:data:`TEXT_SURFACE` consumer files. Like the conc tier it always
analyzes the full surface — a producer and its consumer are usually in
different files, so path subsets would fabricate drift.
"""

from __future__ import annotations

import re
from pathlib import Path
from typing import Dict, Iterable, List, Optional, Tuple

from apex_tpu.analysis.contract.contract_rules import CONTRACT_RULES
from apex_tpu.analysis.contract.extract import ContractIndex, build_index
from apex_tpu.analysis.suppressions import Suppressions, _parse_rules
from apex_tpu.analysis.walker import Finding, ModuleIndex

#: the non-python consumer surface, relative to the repo root — docs
#: catalogs (markdown tables) and the golden Prometheus exposition
TEXT_SURFACE = (
    "docs/observability.md",
    "docs/http.md",
    "docs/router.md",
    "tests/golden/observability.prom",
)

_TEXT_PRAGMA = re.compile(r"tpu-lint:\s*disable=([a-zA-Z0-9_,\- ]+)")


class TextSuppressions:
    """Inline-suppression pragmas for non-python files (markdown, prom)
    — same syntax, found by line regex instead of tokenize. A pragma
    covers its own line and the next one, so a table row can be
    suppressed by an HTML comment (``<!-- tpu-lint: disable=rule --
    why -->``) on the line above it."""

    def __init__(self, text: str):
        self._by_line: Dict[int, frozenset] = {}
        for i, line in enumerate(text.splitlines(), 1):
            m = _TEXT_PRAGMA.search(line)
            if not m:
                continue
            rules = _parse_rules(m.group(1))
            if not rules:
                continue
            for ln in (i, i + 1):
                self._by_line[ln] = self._by_line.get(
                    ln, frozenset()) | rules
        self.count = len(self._by_line)

    def covers(self, finding: Finding) -> bool:
        last = max(finding.line, finding.end_line or finding.line)
        for ln in range(finding.line, last + 1):
            rules = self._by_line.get(ln, ())
            if finding.rule in rules or "all" in rules:
                return True
        return False


def split_surface(sources: Dict[str, str]
                  ) -> Tuple[Dict[str, str], Dict[str, str]]:
    py = {k: v for k, v in sources.items() if k.endswith(".py")}
    texts = {k: v for k, v in sources.items() if not k.endswith(".py")}
    return py, texts


def build_contract_index(sources: Dict[str, str], *,
                         modules: Optional[Dict[str, ModuleIndex]] = None,
                         ) -> Tuple[ContractIndex, List[Finding]]:
    """Index one surface; returns the index and any parse-error
    findings. ``modules`` supplies an already-parsed python half (what
    ``--diff`` uses so one parse feeds all source-only tiers — the
    caller then owns its parse-error findings)."""
    from apex_tpu.analysis.cli import parse_sources

    py, texts = split_surface(sources)
    findings: List[Finding] = []
    if modules is None:
        modules, findings = parse_sources(py)
    return build_index(modules, texts), findings


def analyze_contract_sources(sources: Dict[str, str], *,
                             select: Optional[Iterable[str]] = None,
                             modules: Optional[
                                 Dict[str, ModuleIndex]] = None,
                             ) -> Tuple[List[Finding], int]:
    """Run the contract rules over an in-memory ``{rel path: content}``
    map (python and text files together); returns ``(surviving
    findings, #suppressed)``."""
    chosen = set(select) if select is not None else set(CONTRACT_RULES)
    unknown = chosen - set(CONTRACT_RULES)
    if unknown:
        raise ValueError(
            f"unknown contract rule(s): {', '.join(sorted(unknown))}")
    index, findings = build_contract_index(sources, modules=modules)
    raw: List[Finding] = []
    for name in sorted(chosen):
        raw.extend(CONTRACT_RULES[name].check(index))
    suppressed = 0
    supp_cache: Dict[str, object] = {}
    for f in raw:
        supp = supp_cache.get(f.path)
        if supp is None:
            content = sources.get(f.path, "")
            supp = Suppressions(content) if f.path.endswith(".py") \
                else TextSuppressions(content)
            supp_cache[f.path] = supp
        if supp.covers(f):
            suppressed += 1
        else:
            findings.append(f)
    return findings, suppressed


def read_text_surface(root) -> Dict[str, str]:
    """The :data:`TEXT_SURFACE` files that exist under ``root``."""
    out: Dict[str, str] = {}
    base = Path(root).resolve()
    for rel in TEXT_SURFACE:
        p = base / rel
        if p.is_file():
            try:
                out[rel] = p.read_text(encoding="utf-8",
                                       errors="replace")
            except OSError:
                continue
    return out


def analyze_contract(root, *, select: Optional[Iterable[str]] = None,
                     ) -> Tuple[List[Finding], int]:
    """Disk-backed run: the default python lint surface plus the text
    consumer surface under ``root``."""
    from apex_tpu.analysis.cli import read_sources

    sources, findings = read_sources(Path(root).resolve())
    merged = dict(sources)
    merged.update(read_text_surface(root))
    more, suppressed = analyze_contract_sources(merged, select=select)
    findings.extend(more)
    return findings, suppressed
