"""apex_tpu.fp16_utils — legacy fp16 helpers (reference: apex/fp16_utils/).

The reference predates apex.amp; kept for API parity. On TPU the half type
defaults to bf16.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from apex_tpu.amp.scaler import LossScaler

# reference: apex/fp16_utils/loss_scaler.py — static & dynamic scalers
DynamicLossScaler = LossScaler


def network_to_half(params, half_dtype=jnp.bfloat16):
    """Reference: apex/fp16_utils/fp16util.py:network_to_half — cast floating
    leaves to half, keeping norm-ish params fp32 (the reference composes
    ``BN_convert_float(network.half())``; same composition here)."""
    halved = jax.tree.map(
        lambda x: x.astype(half_dtype) if jnp.issubdtype(x.dtype, jnp.floating) else x,
        params,
    )
    return BN_convert_float(halved)


def BN_convert_float(params):
    """Reference: fp16util.py:BN_convert_float — restore norm params to fp32.
    Heuristic: leaves whose path mentions a normalization layer."""
    from apex_tpu.amp.policy import is_norm_param_name
    from apex_tpu.optimizers.common import path_name

    flat, treedef = jax.tree_util.tree_flatten_with_path(params)
    out = []
    for path, leaf in flat:
        if is_norm_param_name(path_name(path)) and jnp.issubdtype(leaf.dtype, jnp.floating):
            out.append(leaf.astype(jnp.float32))
        else:
            out.append(leaf)
    return jax.tree_util.tree_unflatten(treedef, out)


def master_params_to_model_params(model_params, master_params):
    """Reference: fp16util.py — cast fp32 masters into the model dtypes."""
    return jax.tree.map(lambda mp, m: m.astype(mp.dtype), model_params, master_params)


def model_grads_to_master_grads(model_grads):
    """Reference: fp16util.py — upcast half grads to fp32."""
    return jax.tree.map(lambda g: g.astype(jnp.float32), model_grads)


class FP16_Optimizer:
    """Reference: apex/fp16_utils/fp16_optimizer.py — wraps an optimizer with
    fp32 master weights + (dynamic) loss scaling. Our fused optimizers already
    hold flat fp32 masters, so this is a thin scaler shim around them."""

    def __init__(self, init_optimizer, static_loss_scale=1.0,
                 dynamic_loss_scale=False, dynamic_loss_args=None, verbose=False):
        self.optimizer = init_optimizer
        scale = "dynamic" if dynamic_loss_scale else static_loss_scale
        self.loss_scaler = LossScaler(scale, **(dynamic_loss_args or {}))
        if hasattr(init_optimizer, "attach_amp_scaler"):
            init_optimizer.attach_amp_scaler(self.loss_scaler)

    @property
    def loss_scale(self):
        return float(self.loss_scaler.state.scale)

    def scale_loss(self, loss):
        return self.loss_scaler.scale_loss(loss)

    def step(self, grads, **kw):
        return self.optimizer.step(grads, **kw)

    def zero_grad(self, set_to_none=True):
        self.optimizer.zero_grad(set_to_none)

    def state_dict(self):
        return {"optimizer": self.optimizer.state_dict(),
                "loss_scaler": self.loss_scaler.state_dict()}

    def load_state_dict(self, sd):
        self.optimizer.load_state_dict(sd["optimizer"])
        self.loss_scaler.load_state_dict(sd["loss_scaler"])
