"""Async serving front-end over the paged decode engine: streaming
ingest, priorities/deadlines, and page-spilling preemption.

``PagedDecodeEngine.run()`` drains a *fixed* request list; production
traffic is an open stream. :class:`ServingFrontend` is the layer that
turns the engine into a server:

- **Ingest** — ``submit(request)`` is thread-safe and returns a
  :class:`StreamHandle` immediately; per-token results are pushed to the
  handle as decode chunks retire, and ``result()`` blocks for the full
  output. The pump (below) may run on a background thread (``start()``)
  or be driven synchronously (``drain()`` — what ``run()`` does).
- **Priorities/deadlines** — the pending queue is ordered by the
  injected :class:`~apex_tpu.serving.policy.PriorityDeadlinePolicy`
  (priority desc, then earliest deadline, then arrival). ``deadline_ms``
  is a TTFT SLO; misses are counted (``serving.deadline_misses``), never
  dropped.
- **Preemption** — when a higher-priority request is blocked (no slot or
  pages) and the policy says it cannot wait, the lowest-priority active
  slot is stopped at a sync boundary and its FULL pages are released
  through the prefix-cache insert path (``release_slot`` with the tree's
  keep mask) — the victim's computed prefix survives as cached pages
  instead of being discarded. The victim re-enters the queue with its
  generated-so-far tokens folded into its prompt; its resume admission
  walks the radix tree, points its block table at the spilled pages, and
  re-prefills only the (≤ one page) tail — preemption-by-spill, cheaper
  than vLLM's discard-and-recompute whenever the cache survives. With
  ``prefix_cache=False`` preemption degrades to exactly that
  discard-and-recompute. Greedy outputs are token-identical with
  preemption on or off (the resume re-derives nothing: cached pages
  replay bitwise-stored K/V; the recompute path re-runs the same
  prefill).
- **The pump** — the engine's jitted ``sync_every``-step decode chunk is
  dispatched FIRST each iteration; the host then harvests the *previous*
  chunk's tokens, retires finished slots, streams results, and admits
  new work while the device executes — double-buffered host work. All
  cache mutations are async dispatches on one device stream, so program
  order keeps them correct: a retiring slot is done-frozen (EOS/budget
  masks flip on device) during the in-flight chunk, its writes land only
  at its frozen garbage position (never inside a cacheable full page),
  and its release/realloc are queued after the chunk. The price is that
  a slot freed by chunk N's harvest starts its next request at chunk
  N+2, not N+1 — one chunk of pipeline bubble per handoff, paid back by
  the device never idling through host bookkeeping.

The frontend owns no compiled programs and no pool state — it drives the
engine's (``_admit_fn`` / ``_admit_shared_fn`` / ``_step_fn``), so
``run()`` reimplemented over the frontend exercises the same compile-key
contracts the lint harness binds (``analysis_cases()`` traces
:meth:`ServingFrontend.admission_program` /
:meth:`ServingFrontend.decode_program` — shared accessors, not mirrors).
That program-seam discipline is also what makes tensor parallelism
transparent here: a :class:`~apex_tpu.serving.tp.TensorParallelPagedEngine`
hands the pump shard_map-wrapped programs over its mesh, the pump's
host-side reads (block tables, free counts, harvested tokens) see
replicated values, and nothing in this module knows the chip count
(``stats()`` reports it as ``tp_world`` so benches can divide through).
"""

from __future__ import annotations

import itertools
import queue as _queue
import threading
import time
from collections import deque
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from apex_tpu.obs import compile_watch
from apex_tpu.obs import fleet
from apex_tpu.obs.spans import SpanTracer
from apex_tpu.ops._dispatch import round_up
from apex_tpu.serving import kv_pool
from apex_tpu.serving.policy import PriorityDeadlinePolicy
from apex_tpu.serving.scheduler import (_RUN_COUNTERS, _RUN_HISTOGRAMS,
                                        Request, _bucket_match_pages,
                                        prompt_bucket)
from apex_tpu.utils import metrics

__all__ = ["ServingError", "ServingFrontend", "StreamHandle"]

#: sentinel closing a handle's token stream
_END = object()


def _stack_tiles(payloads, chunk: int):
    """Stack per-page host-tier payloads (per-layer dicts of one page's
    K/V tiles + scales) into one ``kv_pool.promote_pages`` tile batch:
    per-layer arrays of leading dim ``chunk``, zero-padded past the live
    pages (the padded rows scatter to the null-page sink)."""
    out = []
    for li in range(len(payloads[0])):
        lc = {}
        for name in payloads[0][li]:
            a = np.stack([p[li][name] for p in payloads])
            if a.shape[0] < chunk:
                a = np.concatenate(
                    [a, np.zeros((chunk - a.shape[0],) + a.shape[1:],
                                 a.dtype)])
            lc[name] = a
        out.append(lc)
    return out


class ServingError(RuntimeError):
    """Terminal serving failure delivered to a :class:`StreamHandle`:
    the pump died (engine fault, injected kill, scheduler deadlock), the
    frontend refused the request (draining, fault-injected admission
    reject), or — at the router layer (``serving/router.py``) — every
    failover attempt was exhausted. A handle that fails raises this from
    ``result()`` AND from iteration/``get()``, so a streaming consumer
    can never block forever on a dead engine."""

#: pump pipeline timing series (run-local percentiles in ``stats()``;
#: cumulative distributions in the engine-labeled histograms):
#: ``dispatch_ready_ms`` = device wall time of one decode chunk from
#: dispatch to the host observing its tokens, ``host_work_ms`` = the
#: host side of one pump iteration NET of time blocked on the device,
#: ``bubble_ms`` = device idle between a chunk completing and the next
#: dispatch — the direct measurement of whether the double-buffered
#: host work is actually hidden (docs/frontend.md)
_PUMP_SERIES = ("pump.dispatch_ready_ms", "pump.host_work_ms",
                "pump.bubble_ms")


class StreamHandle:
    """One submitted request's streaming view: tokens arrive in order as
    the pump harvests decode chunks; iteration ends when the request
    retires (EOS / token budget) or is cancelled. ``result()`` blocks
    for the complete generated-token array. All methods are thread-safe
    (the pump pushes from its thread, callers consume from theirs)."""

    def __init__(self, request_id):
        self.request_id = request_id
        self._q: "_queue.Queue" = _queue.Queue()
        self._tokens: List[int] = []
        self._lock = threading.Lock()
        self._done = threading.Event()
        self._cancelled = threading.Event()
        self._output: Optional[np.ndarray] = None
        self._error: Optional[BaseException] = None
        # consumption cursor: tokens the consumer has actually taken
        # (``get``/iteration advance it implicitly; ``ack`` explicitly —
        # the HTTP writer acks only after the socket accepted the bytes,
        # so ``unread()`` is the per-connection in-flight-token window
        # the frontend's backpressure spill keys on)
        self._consumed = 0
        self._listener = None            # push/terminate notification

    # -- pump side -----------------------------------------------------------

    def _push(self, tok: int) -> None:
        with self._lock:
            self._tokens.append(tok)
            listener = self._listener
        self._q.put(tok)
        if listener is not None:
            listener()                   # outside the lock, by contract

    def _finish(self, output: np.ndarray) -> None:
        self._output = output
        self._done.set()
        self._q.put(_END)
        with self._lock:
            listener = self._listener
        if listener is not None:
            listener()

    def _fail(self, exc: BaseException) -> None:
        # terminal errors surface as ServingError everywhere (result,
        # get, iteration) with the original failure chained as the cause
        if not isinstance(exc, ServingError):
            wrapped = ServingError(
                f"request {self.request_id!r} failed: {exc!r}")
            wrapped.__cause__ = exc
            exc = wrapped
        self._error = exc
        self._done.set()
        self._q.put(_END)
        with self._lock:
            listener = self._listener
        if listener is not None:
            listener()

    # -- caller side ---------------------------------------------------------

    @property
    def done(self) -> bool:
        return self._done.is_set()

    @property
    def cancelled(self) -> bool:
        return self._cancelled.is_set()

    def cancel(self) -> None:
        """Request cancellation: a pending request is dropped, an active
        one retires at the next sync boundary (its stream terminates and
        its pages free/spill normally). Idempotent; the already-streamed
        tokens remain the handle's output."""
        self._cancelled.set()

    def tokens_so_far(self) -> List[int]:
        with self._lock:
            return list(self._tokens)

    def unread(self) -> int:
        """Tokens pushed but not yet consumed — the per-consumer
        in-flight window. ``get``/iteration consume implicitly;
        adapters that read via :meth:`tokens_so_far` (the asyncio
        bridge) must :meth:`ack` explicitly."""
        with self._lock:
            return len(self._tokens) - self._consumed

    def ack(self, n: int) -> None:
        """Mark the first ``n`` streamed tokens consumed (monotonic;
        clamped to what has been pushed). The HTTP writer calls this
        after the socket accepted a token's bytes — a stalled reader
        stops acking and :meth:`unread` grows until the frontend spills
        the slot."""
        with self._lock:
            self._consumed = max(self._consumed,
                                 min(n, len(self._tokens)))

    def set_listener(self, fn) -> None:
        """Register one callback fired (outside the handle lock, on the
        pusher's thread) after every push/finish/fail — the seam the
        asyncio adapter uses to wake its event loop. Fires once
        immediately if the stream already has tokens or terminated, so
        a late registration can never miss the wake-up."""
        with self._lock:
            self._listener = fn
            pending = bool(self._tokens) or self._done.is_set()
        if pending and fn is not None:
            fn()

    @property
    def error(self) -> Optional[BaseException]:
        """The terminal :class:`ServingError`, if the request failed
        (readable once ``done``; ``result()``/iteration re-raise it)."""
        return self._error

    def get(self, timeout: Optional[float] = None) -> Optional[int]:
        """Next token, or None once the stream has terminated. Raises
        ``queue.Empty`` on timeout and the terminal
        :class:`ServingError` if the request failed — a consumer
        blocked on a stream whose engine died is woken and raised at,
        never left hanging."""
        tok = self._q.get(timeout=timeout)
        if tok is _END:
            self._q.put(_END)            # keep the stream terminated
            if self._error is not None:
                raise self._error
            return None
        with self._lock:
            self._consumed += 1          # queue order == push order
        return tok

    def __iter__(self):
        while True:
            tok = self.get()
            if tok is None:
                return
            yield tok

    def result(self, timeout: Optional[float] = None) -> np.ndarray:
        """Block until the request finishes; the generated tokens (up to
        and including EOS), truncated at the cancellation point for a
        cancelled request. Re-raises a pump failure."""
        if not self._done.wait(timeout):
            raise TimeoutError(
                f"request {self.request_id!r} still running")
        if self._error is not None:
            raise self._error
        return self._output


class _Entry:
    """Pump-internal request state, reused across preempt/resume cycles:
    ``prompt`` is the CURRENT segment's prompt (the original plus every
    previously generated token after a preemption), ``prev`` the tokens
    generated by earlier segments, ``seg_tokens`` the current segment's.
    ``joined`` is the first decode-chunk index whose harvested tokens
    belong to this segment (pipelining: a chunk dispatched before the
    admission carries the PREVIOUS occupant's frozen fill tokens)."""

    __slots__ = ("idx", "handle", "prompt", "total_new", "priority",
                 "deadline_at", "arrival", "seq", "resume", "prev",
                 "seg_tokens", "nodes", "n_private", "joined",
                 "first_token_seen", "tpot_slo", "deadline_missed",
                 "win_dropped", "prefilling", "pf_pos", "pf_key",
                 "pf_samp0")

    def __init__(self, idx, handle, prompt, total_new, priority,
                 deadline_at, arrival, seq):
        self.idx = idx
        self.handle = handle
        self.prompt = prompt
        self.total_new = total_new
        self.priority = priority
        self.deadline_at = deadline_at
        self.arrival = arrival
        self.seq = seq
        self.resume = False
        self.prev: List[int] = []
        self.seg_tokens: List[int] = []
        self.nodes: list = []
        self.n_private = 0
        self.joined = 0
        self.first_token_seen = False
        self.tpot_slo = None
        self.deadline_missed = False
        self.win_dropped = 0             # leading block-table entries
        #                                  already window-dropped
        self.prefilling = False          # chunked prefill in progress
        self.pf_pos = 0                  # prompt tokens fed so far
        self.pf_key = None               # req_key held until decode joins
        self.pf_samp0 = 0

    @property
    def s0(self) -> int:
        return int(self.prompt.shape[0])

    @property
    def seg_new(self) -> int:
        """This segment's token budget (total minus earlier segments)."""
        return self.total_new - len(self.prev)

    @property
    def generated(self) -> int:
        return len(self.prev) + len(self.seg_tokens)


class _Chunk:
    """An in-flight (dispatched, unharvested) decode chunk.
    ``toks_np``/``t_done`` cache the materialized tokens and the moment
    the host first observed completion — stamped as early as possible
    (an admission syncing on the pool materializes the chunk first) so
    ``decode_step_ms`` measures the chunk, not later host work."""

    __slots__ = ("toks", "idx", "t0", "toks_np", "t_done")

    def __init__(self, toks, idx, t0):
        self.toks = toks
        self.idx = idx
        self.t0 = t0
        self.toks_np = None
        self.t_done = None


class ServingFrontend:
    """Streaming ingest + priority/deadline scheduling + preemption over
    one :class:`~apex_tpu.serving.scheduler.PagedDecodeEngine`.

    One frontend drives one engine; ``engine.run()`` constructs a fresh
    frontend per call (so its stats and tracer stay run-scoped), while a
    server holds a long-lived one with a background pump thread. The
    pump itself is single-threaded — only ``submit``/``cancel`` cross
    threads, through the ingest lock and the handles.
    """

    def __init__(self, engine, *, policy: Optional[PriorityDeadlinePolicy]
                 = None, tracer: Optional[SpanTracer] = None,
                 clock=time.perf_counter, fault_hook=None,
                 backpressure_window: Optional[int] = None):
        self.engine = engine
        # per-consumer in-flight-token bound (None = unbounded, the
        # pre-HTTP behavior): an active slot whose handle has more than
        # this many unconsumed tokens is spilled through the preemption
        # path — pages into the radix cache, slot freed — and held out
        # of re-admission until the consumer catches back up to half the
        # window. Pool pages are never pinned by a stalled socket.
        if backpressure_window is not None and backpressure_window < 1:
            raise ValueError("backpressure_window must be >= 1")
        self.backpressure_window = backpressure_window
        # fault-injection seam (serving/faults.py): an object with
        # ``on_pump(frontend)`` (start of every pump iteration — may
        # raise to kill the pump, or sleep to stall it) and
        # ``on_submit(frontend, request)`` (may raise ServingError to
        # reject the submission). First-class so chaos scenarios hook
        # the real seams instead of monkeypatching; None = no faults.
        self.fault_hook = fault_hook
        self.policy = policy if policy is not None \
            else PriorityDeadlinePolicy()
        self.clock = clock
        self.tracer = tracer if tracer is not None else SpanTracer()
        engine.tracer = self.tracer      # the engine's "last run" tracer
        n = engine.num_slots
        self._tok = jnp.zeros((n,), jnp.int32)
        self._done = jnp.ones((n,), bool)
        self._n_left = jnp.zeros((n,), jnp.int32)
        self._samp_i = jnp.zeros((n,), jnp.int32)
        self._req_keys = jnp.broadcast_to(engine.rng,
                                          (n,) + engine.rng.shape)
        self._ingest_lock = threading.Lock()
        self._ingest: deque = deque()
        self._pending: List[_Entry] = []
        self._active: Dict[int, _Entry] = {}
        self._inflight: Optional[_Chunk] = None
        self._chunk = 0
        self._submit_seq = itertools.count()
        self._pool_dirty = False
        self.peak_slots = 0
        self.peak_queue_depth = 0
        labels = engine.obs_labels
        self._C = {name: metrics.counter(f"serving.{name}", labels=labels)
                   for name in _RUN_COUNTERS}
        self._c0 = {name: c.value for name, c in self._C.items()}
        self._H = {name: metrics.histogram(f"serving.{name}", labels=labels)
                   for name in _RUN_HISTOGRAMS}
        self._per_run = {name: [] for name in _RUN_HISTOGRAMS
                         + _PUMP_SERIES}
        self._occ = metrics.gauge("serving.slots_in_use", labels=labels)
        self._qdepth = metrics.gauge("serving.queue_depth", labels=labels)
        # pump pipeline timing (docs/frontend.md "Measuring the pump"):
        # chunk device time is labeled by phase — a preempt-flush chunk
        # is harvested synchronously mid-iteration and must not pollute
        # the steady-state distribution
        self._pump_H = {
            (name, phase): metrics.histogram(
                name, labels={**labels, "phase": phase})
            for name in ("pump.dispatch_ready_ms",)
            for phase in ("steady", "preempt")}
        self._host_H = metrics.histogram("pump.host_work_ms",
                                         labels=labels)
        self._bubble = metrics.gauge("pump.bubble_ms", labels=labels)
        self._last_ready: Optional[float] = None
        self._wait_s = 0.0
        # TPOT-SLO burn rate: (time, missed) per SLO-carrying retirement
        # inside the policy's rolling window (pump-confined state)
        self._slo_window: deque = deque()
        self._slo_burn = metrics.gauge("serving.slo_burn", labels=labels)
        # recompile watcher (docs/observability.md): process-wide hooks,
        # per-frontend delta window for stats + storm warnings
        self._watch = compile_watch.watcher()
        self._jit0 = self._watch.counts()
        self._jit_totals0 = self._watch.totals()
        self._storm_seen: set = set()
        self._thread: Optional[threading.Thread] = None
        self._stop_evt = threading.Event()
        self._work_evt = threading.Event()
        self._failure: Optional[BaseException] = None
        self._accepting = True           # cleared by shutdown()

    # --- ingest -------------------------------------------------------------

    def submit(self, request: Request, *,
               request_id: Optional[int] = None) -> StreamHandle:
        """Enqueue one request; returns its streaming handle immediately.

        Thread-safe. Validates the request's position/page budget up
        front (``ValueError`` surfaces to the submitter, never to the
        pump). ``request_id`` defaults to a per-frontend sequence number;
        it keys the tracer's lifecycle AND the request's sampling stream
        (``fold_in(rng, request_id)``), so two frontends given the same
        ids and rng draw identical streams."""
        # lock-free fast-fail is intentional double-checked locking (one
        # snapshot read): the locked re-check below is authoritative;
        # this only saves validation work on an already-dead frontend
        # tpu-lint: disable=conc-unguarded-shared-field -- benign race
        failure = self._failure
        if failure is not None:
            raise ServingError("frontend pump has failed") from failure
        self.engine._validate_request(request)
        if self.fault_hook is not None:
            # admission-reject faults raise HERE, before any state is
            # touched — the submitter (or the router's retry path) sees
            # a clean ServingError and nothing dangles
            self.fault_hook.on_submit(self, request)
        seq = next(self._submit_seq)
        idx = request_id if request_id is not None else seq
        now = self.clock()
        arrival = request.arrival_time if request.arrival_time is not None \
            else now
        deadline_at = (arrival + request.deadline_ms * 1e-3
                       if request.deadline_ms is not None else None)
        prompt = np.asarray(request.prompt, np.int32).reshape(-1)
        handle = StreamHandle(idx)
        entry = _Entry(idx, handle, prompt, request.max_new_tokens,
                       request.priority, deadline_at, arrival, seq)
        entry.tpot_slo = request.tpot_slo_ms
        # trace propagation (docs/observability.md "Fleet plane"): the
        # enqueue event binds this request id to its fleet-wide trace —
        # a routed request arrives with the router's mint, a direct
        # submit mints here, and stitch_traces() joins every replica's
        # spans on it
        trace_id = request.trace_id if request.trace_id is not None \
            else fleet.mint_trace_id()
        self.tracer.event(idx, "enqueue",
                          prompt_tokens=int(prompt.shape[0]),
                          max_new_tokens=request.max_new_tokens,
                          priority=request.priority,
                          deadline_ms=request.deadline_ms,
                          trace_id=trace_id)
        with self._ingest_lock:
            # re-check under the lock: a pump failure drains the ingest
            # queue under this lock, so an entry either lands before the
            # drain (and is failed with the rest) or raises here — a
            # handle can never be left dangling un-finished
            if self._failure is not None:
                raise ServingError("frontend pump has failed") \
                    from self._failure
            if not self._accepting:
                raise ServingError("frontend is shutting down")
            self._ingest.append(entry)
            depth = len(self._ingest) + len(self._pending)
            # peak tracking is a read-modify-write; two racing submits
            # outside the lock could each lose the other's peak
            self.peak_queue_depth = max(self.peak_queue_depth, depth)
        self._qdepth.set(depth)
        self._work_evt.set()
        return handle

    @property
    def queue_depth(self) -> int:
        with self._ingest_lock:
            return len(self._ingest) + len(self._pending)

    @property
    def active_slots(self) -> int:
        """Slots currently decoding (an instantaneous read — the pump
        owns ``_active``; ``len`` of a dict is atomic in CPython)."""
        return len(self._active)

    @property
    def pump_alive(self) -> bool:
        """True while the background pump thread is running (the
        ``/healthz`` liveness bit; a synchronously driven frontend
        reports False — its caller IS the pump)."""
        thread = self._thread
        return thread is not None and thread.is_alive()

    @property
    def failure(self) -> Optional[BaseException]:
        """The pump's terminal failure, if any (``/healthz`` surfaces
        its repr)."""
        with self._ingest_lock:
            return self._failure

    def _drain_ingest(self) -> None:
        with self._ingest_lock:
            while self._ingest:
                self._pending.append(self._ingest.popleft())

    # --- lint-harness accessors (shared with analysis/ir/harness.py) --------

    def admission_program(self, s0: int):
        """The compiled cold-admission program + compile-key bucket the
        pump uses for a raw prompt length. The IR lint harness traces
        THIS accessor at two same-bucket lengths
        (``ir-compile-key-cardinality``), so the contract binds the
        frontend's real bucketing — shared with ``scheduler.run()``'s
        path, never mirrored."""
        eng = self.engine
        bucket = prompt_bucket(s0, eng.page_size,
                               eng.cfg.max_position_embeddings)
        if eng.draft_len:
            return eng._spec_admit_fn(bucket), bucket
        return eng._admit_fn(bucket), bucket

    def decode_program(self):
        """The jitted ``sync_every``-step decode chunk the pump
        dispatches (the engine's ``_step_fn`` — or its speculative twin
        ``_spec_step_fn`` when the engine drafts — one program,
        shared)."""
        eng = self.engine
        return eng._spec_step_fn() if eng.draft_len else eng._step_fn()

    # --- the pump -----------------------------------------------------------

    # tpu-lint: host-boundary -- the pump is the host scheduling loop
    # driving the jitted admit/step programs; it syncs at every chunk
    # harvest by contract and is never traced
    def pump(self) -> bool:
        """One scheduler iteration: dispatch the next decode chunk, then
        (overlapping its device execution) harvest the previous chunk —
        retire/stream/spill — and run admission/preemption. Returns True
        while work remains. Raises ``RuntimeError`` on scheduler
        deadlock (a queued request that cannot be admitted even with
        every slot vacant and every evictable page evicted).

        Any exception out of the pump — an engine fault, a deadlock, an
        injected kill — is TERMINAL: the failure is published (later
        ``submit`` calls raise it) and every live handle fails with a
        :class:`ServingError` before the exception propagates, so a
        consumer blocked on ``result()``/iteration is woken within one
        boundary instead of hanging forever (the pump-death contract;
        same path for the synchronous and background drivers)."""
        try:
            if self.fault_hook is not None:
                self.fault_hook.on_pump(self)
            return self._pump_impl()
        except BaseException as exc:          # noqa: BLE001 — terminal
            self._fail_all(exc)
            raise

    def _fail_all(self, exc: BaseException) -> None:
        """Publish the pump's terminal failure and fail every live
        handle (ingest + pending + active). Idempotent — the first
        failure wins; the ingest queue is claimed atomically with the
        publication so ``submit`` can never leave a handle dangling."""
        with self._ingest_lock:
            if self._failure is not None:
                return
            self._failure = exc
            victims = list(self._ingest)
            self._ingest.clear()
        victims += list(self._pending) + list(self._active.values())
        self._pending.clear()
        self._active.clear()
        self._inflight = None
        for entry in victims:
            entry.handle._fail(exc)

    # tpu-lint: host-boundary -- body of pump() (see above)
    def _pump_impl(self) -> bool:
        eng = self.engine
        t_iter0 = self.clock()
        self._wait_s = 0.0
        self._drain_ingest()
        prev, self._inflight = self._inflight, None
        if any(not e.prefilling for e in self._active.values()):
            # the device sat idle iff everything dispatched so far has
            # already completed: either nothing was in flight (the last
            # chunk's completion time is in _last_ready), or the chunk
            # still nominally in flight was materialized early by an
            # admission's pool read. The gap from that completion to
            # this dispatch is the pipeline bubble the double-buffering
            # exists to hide — pay attention when it grows.
            idle_since = prev.t_done if prev is not None \
                else self._last_ready
            self._dispatch()
            if idle_since is not None:
                bubble_ms = max(0.0,
                                (self._inflight.t0 - idle_since) * 1e3)
                self._bubble.set(bubble_ms)
                self._per_run["pump.bubble_ms"].append(bubble_ms)
                self._last_ready = None
        if eng.host_tier is not None:
            # demote copies dispatched at earlier boundaries ride the
            # double-buffered host-work slot: the next chunk is already
            # in flight above, so converting the gathered tiles to host
            # entries here overlaps the device, not the pipeline
            eng.host_tier.drain()
        if prev is not None:
            self._harvest(prev)
        self._backpressure_spill()
        self._drop_window_pages()
        self._advance_prefills()
        admitted = self._admission()
        if (any(not self._bp_held(e) for e in self._pending)
                and not self._active and self._inflight is None
                and not admitted):
            raise RuntimeError(
                "scheduler deadlock: queued request cannot be admitted "
                "even with every slot vacant and every evictable cached "
                "page evicted (pool too small for its page demand?)")
        if self._pool_dirty:
            kv_pool.observe_pool(eng.cache, labels=eng.obs_labels)
            self._pool_dirty = False
        self._qdepth.set(len(self._pending))
        if prev is not None or admitted:
            # host cost of this iteration net of time blocked on the
            # device — with the chunk in flight, this is the work the
            # pipeline hides (bubble_ms above is what leaked through)
            host_ms = max(0.0, (self.clock() - t_iter0 - self._wait_s)
                          * 1e3)
            self._host_H.observe(host_ms)
            self._per_run["pump.host_work_ms"].append(host_ms)
        self._check_compile_storm()
        # a pending entry held by backpressure does not count as live
        # work: the pump has nothing to do for it until its consumer
        # catches up, so the background loop falls back to its bounded
        # re-poll (work_evt wait) instead of busy-spinning, and a
        # synchronous drain() returns rather than hanging on a socket
        alive = bool(self._active or self._inflight
                     or any(not self._bp_held(e) for e in self._pending))
        if not alive:
            self._last_ready = None      # idle gaps are not bubbles
        return alive

    # tpu-lint: host-boundary -- synchronous drive of the pump loop
    def drain(self) -> None:
        """Pump until every submitted request has retired (what
        ``engine.run()`` does); leaves the pool gauges fresh."""
        while self.pump():
            pass
        self._occ.set(0)
        kv_pool.observe_pool(self.engine.cache, labels=self.engine.obs_labels)

    def start(self) -> None:
        """Run the pump on a background thread until ``stop()``; a pump
        failure (e.g. deadlock) marks every live handle failed and is
        re-raised by later ``submit`` calls."""
        if self._thread is not None:
            raise RuntimeError("frontend already started")
        self._stop_evt.clear()

        def loop():
            try:
                while not self._stop_evt.is_set():
                    if not self.pump():
                        self._work_evt.clear()
                        self._occ.set(0)
                        self._work_evt.wait(timeout=0.01)
            except BaseException as exc:          # noqa: BLE001
                # pump() already published the failure and failed every
                # live handle; this covers an exception in the loop
                # bookkeeping itself (idempotent either way)
                self._fail_all(exc)

        self._thread = threading.Thread(target=loop, daemon=True,
                                        name="serving-frontend-pump")
        self._thread.start()

    def stop(self, timeout: Optional[float] = 30.0) -> None:
        """Stop the background pump thread (in-flight device work is
        left to complete; pending requests stay queued). For a clean
        end-of-life under load — queued + active + mid-stream requests
        resolved, zero leaked pages, zero dangling threads — use
        :meth:`shutdown` instead."""
        if self._thread is None:
            return
        self._stop_evt.set()
        self._work_evt.set()
        self._thread.join(timeout)
        self._thread = None

    def _has_work(self) -> bool:
        return bool(self.queue_depth or self._active or self._inflight)

    def _cancel_live(self) -> None:
        """Cancel every live handle (ingest snapshot under the lock;
        pending/active are pump-confined lists — ``list()`` snapshots
        are safe to iterate from any thread)."""
        with self._ingest_lock:
            victims = [e.handle for e in self._ingest]
        victims += [e.handle for e in list(self._pending)]
        victims += [e.handle for e in list(self._active.values())]
        for handle in victims:
            handle.cancel()

    def shutdown(self, deadline_s: float = 30.0, *,
                 mode: str = "drain") -> None:
        """Graceful end-of-life under load: stop accepting (later
        ``submit`` raises :class:`ServingError`), then resolve every
        queued + active + mid-stream request deterministically —
        ``mode="drain"`` finishes them (falling back to cancellation
        once ``deadline_s`` expires), ``mode="cancel"`` cancels them
        up front (each stream terminates at its next sync boundary with
        the already-streamed tokens as its truncated output). Either
        way every handle reaches ``done``, every non-cached pool page
        returns to the free stack, and the background thread (if any)
        is joined — zero dangling threads. A pump failure during the
        wind-down has already failed the handles; shutdown still stops
        the thread and returns."""
        if mode not in ("drain", "cancel"):
            raise ValueError(f"shutdown mode must be 'drain' or "
                             f"'cancel', got {mode!r}")
        with self._ingest_lock:
            self._accepting = False
        deadline = self.clock() + deadline_s
        if mode == "cancel":
            self._cancel_live()
        if self._thread is not None:
            # the background pump drives itself; wait for quiescence,
            # cancelling the stragglers once the deadline passes
            cancelled = mode == "cancel"
            while (self._has_work() and self.pump_alive
                   and self.failure is None):
                if self.clock() >= deadline:
                    if cancelled:
                        break
                    self._cancel_live()
                    cancelled = True
                    deadline = self.clock() + max(deadline_s, 1.0)
                time.sleep(0.002)
            self.stop()
        # we own the pump now (or always did): drive what remains.
        # Draining is deadline-bounded; once everything is cancelled the
        # loop is bounded by a pump budget instead of wall time (cancels
        # resolve within ~two boundaries, and an injected test clock
        # never advances), so shutdown always terminates
        cancelled = mode == "cancel"
        budget: Optional[int] = None
        try:
            while self._has_work():
                if not cancelled and self.clock() >= deadline:
                    self._cancel_live()
                    cancelled = True
                if cancelled:
                    if budget is None:
                        budget = 4 * self.engine.num_slots + 16
                    budget -= 1
                    if budget < 0:
                        break
                if not self.pump():
                    # a drain can go idle with backpressure-HELD entries
                    # still pending (their consumers stalled): keep
                    # waiting for consumption until the deadline flips
                    # us to cancellation; anything else idle is done
                    if cancelled or not any(self._bp_held(e)
                                            for e in self._pending):
                        break
                    time.sleep(0.002)
        except Exception:                # noqa: BLE001 — handles already
            pass                         # failed by pump(); stop cleanly
        leftovers = []
        with self._ingest_lock:
            leftovers += list(self._ingest)
            self._ingest.clear()
        leftovers += list(self._pending) + list(self._active.items())
        self._pending.clear()
        if self._active and self.failure is None:
            # release the stragglers' pages before failing them — the
            # zero-leak contract holds even when the deadline expired
            # with slots still decoding
            for slot, entry in list(self._active.items()):
                self._release_pages(slot, entry)
                self._done = self._done.at[slot].set(True)
            self._active.clear()
        exc = ServingError(f"frontend shutdown ({mode}) deadline "
                           f"expired with requests unresolved")
        for item in leftovers:
            entry = item[1] if isinstance(item, tuple) else item
            entry.handle._fail(exc)
        self._occ.set(0)
        self._qdepth.set(0)
        if self.failure is None:
            kv_pool.observe_pool(self.engine.cache,
                                 labels=self.engine.obs_labels)

    # --- device chunk dispatch/harvest --------------------------------------

    def _dispatch(self) -> None:
        eng = self.engine
        self._chunk += 1
        busy = sum(1 for e in self._active.values()
                   if e.joined <= self._chunk)
        self._C["busy_slot_steps"].inc(busy * eng.sync_every)
        self._C["decode_steps"].inc(eng.sync_every)
        t0 = self.clock()
        if eng.draft_len:
            # speculative chunk: the payload is (target predictions,
            # per-slot per-round acceptance counts) — the harvest emits
            # toks[r, slot, :counts[r, slot]]
            (eng.cache, eng.draft_cache, self._tok, self._done,
             self._n_left, toks, counts) = eng._spec_step_fn()(
                eng.cache, eng.draft_cache, eng.variables,
                eng.draft_variables, self._tok, self._done, self._n_left)
            self._inflight = _Chunk((toks, counts), self._chunk, t0)
        else:
            (eng.cache, self._tok, self._done, self._n_left, self._samp_i,
             toks) = eng._step_fn()(eng.cache, eng.variables, self._tok,
                                    self._done, self._n_left,
                                    self._req_keys, self._samp_i)
            self._inflight = _Chunk(toks, self._chunk, t0)
        self.peak_slots = max(self.peak_slots, len(self._active))
        self._occ.set(len(self._active))

    def _materialize(self, chunk: _Chunk) -> np.ndarray:
        """Block for the chunk's tokens (overlapping whatever device
        work was queued after it) and stamp its completion time, once —
        idempotent, so the earliest host sync that implies the chunk is
        done (harvest, or an admission's pool read) fixes the
        measurement before unrelated host work can inflate it."""
        if chunk.toks_np is None:
            t_enter = self.clock()
            chunk.toks_np = (tuple(np.asarray(t) for t in chunk.toks)
                             if isinstance(chunk.toks, tuple)
                             else np.asarray(chunk.toks))
            chunk.t_done = self.clock()
            # the blocked span counts as device wait, not host work
            self._wait_s += chunk.t_done - t_enter
            self._last_ready = chunk.t_done
        return chunk.toks_np

    def _harvest(self, chunk: _Chunk, *, phase: str = "steady") -> None:
        eng = self.engine
        toks_np = self._materialize(chunk)
        chunk_ms = (chunk.t_done - chunk.t0) * 1e3
        step_ms = chunk_ms / eng.sync_every
        self._H["decode_step_ms"].observe(step_ms)
        self._per_run["decode_step_ms"].append(step_ms)
        self._pump_H[("pump.dispatch_ready_ms", phase)].observe(chunk_ms)
        if phase == "steady":
            # the run percentiles are the STEADY-state device time; a
            # preemption flush harvests mid-chunk and only its labeled
            # histogram keeps that wall time
            self._per_run["pump.dispatch_ready_ms"].append(chunk_ms)
        eos = eng.eos_token_id
        spec = isinstance(toks_np, tuple)
        if spec:
            preds_np, counts_np = toks_np
        for slot in list(self._active):
            entry = self._active[slot]
            if entry.prefilling:
                continue                 # chunked prefill in progress —
            #                             cancellation is handled by
            #                             _advance_prefills
            if entry.handle.cancelled:
                self._retire(slot, cancelled=True)
                self._done = self._done.at[slot].set(True)
                continue
            if entry.joined > chunk.idx:
                continue                 # admitted after this chunk ran
            finished = False
            if spec:
                # per speculative round: the slot's first counts[r]
                # target predictions were accepted+emitted on device
                for r in range(preds_np.shape[0]):
                    cnt = int(counts_np[r, slot])
                    if cnt:
                        self._C["spec_rounds"].inc()
                        self._C["spec_tokens"].inc(cnt)
                    for t in preds_np[r, slot, :cnt]:
                        t = int(t)
                        entry.seg_tokens.append(t)
                        entry.handle._push(t)
                        if ((eos is not None and t == eos)
                                or entry.generated >= entry.total_new):
                            finished = True
                            break
                    if finished:
                        break
            else:
                for t in toks_np[:, slot]:
                    t = int(t)
                    entry.seg_tokens.append(t)
                    entry.handle._push(t)
                    if ((eos is not None and t == eos)
                            or entry.generated >= entry.total_new):
                        finished = True
                        break
            if finished:
                self._retire(slot)
                self._done = self._done.at[slot].set(True)

    def _drop_window_pages(self) -> None:
        """Sliding-window models only: free every active slot's pages
        that fell fully below the attention band — the rolling-cache
        eviction trick at page granularity (``kv_pool.drop_slot_pages``).
        Block-table entry ``j`` is dead once the NEXT query position
        ``p`` satisfies ``(j+1)*page_size - 1 <= p - window``; the band
        only moves forward, so a dead entry stays dead and each page
        frees exactly once. The drop is an async dispatch queued AFTER
        the in-flight decode chunk on the device stream, so program
        order keeps the chunk's banded reads ahead of it."""
        eng = self.engine
        window = eng.window
        if window is None:
            return
        ps = eng.page_size
        for slot, entry in self._active.items():
            # device len at the last harvested boundary = prompt + every
            # decode step run (tok0 samples at admit, writes at step 1);
            # the next query position equals that len
            nxt = entry.s0 + len(entry.seg_tokens) - 1
            upto = max((nxt + 1 - window) // ps, 0)
            if upto > entry.win_dropped:
                eng.cache = eng._drop_jit(eng.cache, jnp.int32(slot),
                                          jnp.int32(upto))
                freed = upto - entry.win_dropped
                entry.win_dropped = upto
                entry.n_private -= freed
                self._C["window_dropped_pages"].inc(freed)
                self._pool_dirty = True

    def _flush(self) -> None:
        """Synchronize the pipeline: harvest the in-flight chunk (if
        any) so every active record's token state is current — the
        precondition for a correct preemption spill."""
        prev, self._inflight = self._inflight, None
        if prev is not None:
            self._harvest(prev, phase="preempt")

    # --- retirement / preemption --------------------------------------------

    def _release_pages(self, slot: int, entry: _Entry) -> None:
        """Return slot ``slot``'s pages with the prefix-cache disposition:
        full written pages (prompt + fed tokens) move into the radix tree
        (so a later match — including this request's own resume — hits),
        the partial tail frees; without a prefix cache everything
        frees."""
        eng = self.engine
        if eng.prefix is None:
            eng.cache = eng._free_jit(eng.cache, jnp.int32(slot))
            if eng.draft_len:
                # the draft pool mirrors the target pool slot-for-slot
                eng.draft_cache = eng._draft_free_jit(eng.draft_cache,
                                                      jnp.int32(slot))
            return
        if entry.prefilling:
            # a mid-prefill release (cancel/shutdown): only the chunks
            # already fed are written — their full pages are cacheable
            written = entry.pf_pos
            seq = entry.prompt[:written]
        else:
            # written K/V = prompt + every token fed while alive (all but
            # the final sampled token); only full pages are shareable
            written = entry.s0 + len(entry.seg_tokens) - 1
            seq = np.concatenate(
                [entry.prompt, np.asarray(entry.seg_tokens[:-1],
                                          np.int32)])
        row = np.asarray(eng.cache["block_tables"][slot])
        keep = eng.prefix.release_and_insert(seq, written, entry.nodes, row)
        eng.cache = eng._release_jit(eng.cache, jnp.int32(slot),
                                     jnp.asarray(keep))

    def _observe_lifecycle(self, idx) -> dict:
        life = self.tracer.lifecycle(idx)
        for name in ("ttft_ms", "tpot_ms", "queue_wait_ms"):
            if name in life:
                self._H[name].observe(life[name])
                self._per_run[name].append(life[name])
        return life

    def _observe_slo(self, entry: _Entry, life: dict, now: float) -> None:
        """The TPOT-SLO check (once, at retirement) + the rolling burn
        gauge over the policy's window: SLO-carrying retirements that
        missed either their TTFT deadline or their TPOT target, as a
        rate. Pump-confined — no lock."""
        missed_tpot = (entry.tpot_slo is not None
                       and life.get("tpot_ms") is not None
                       and life["tpot_ms"] > entry.tpot_slo)
        if missed_tpot:
            self._C["tpot_slo_misses"].inc()
            self.tracer.event(entry.idx, "tpot_slo_miss",
                              tpot_ms=life["tpot_ms"],
                              slo_ms=entry.tpot_slo)
            self.engine.events.emit("tpot_slo_miss", request=entry.idx,
                                    tpot_ms=round(life["tpot_ms"], 3),
                                    slo_ms=entry.tpot_slo)
        if entry.tpot_slo is None and entry.deadline_at is None:
            return
        self._slo_window.append(
            (now, bool(missed_tpot or entry.deadline_missed)))
        horizon = now - self.policy.slo_window_s
        while self._slo_window and self._slo_window[0][0] < horizon:
            self._slo_window.popleft()
        misses = sum(1 for _, m in self._slo_window if m)
        self._slo_burn.set(misses / len(self._slo_window))

    def _retire(self, slot: int, *, cancelled: bool = False) -> None:
        eng = self.engine
        entry = self._active.pop(slot)
        output = np.asarray(entry.prev + entry.seg_tokens, np.int32)
        self._C["retired"].inc()
        n_seg = len(entry.seg_tokens)
        self.tracer.end(entry.idx, "decode", new_tokens=n_seg)
        self.tracer.event(entry.idx, "retire", slot=slot,
                          new_tokens=int(output.shape[0]),
                          cancelled=cancelled)
        eng.events.emit("cancel" if cancelled else "retire",
                        request=entry.idx, slot=slot,
                        new_tokens=int(output.shape[0]))
        life = self._observe_lifecycle(entry.idx)
        if not cancelled:
            self._observe_slo(entry, life, self.clock())
        self._release_pages(slot, entry)
        self._pool_dirty = True
        entry.handle._finish(output)

    def _preempt(self, slot: int) -> None:
        """Stop the victim at this (flushed) sync boundary, spill its
        full pages into the prefix cache, and requeue it for resumption
        with its generated tokens folded into the prompt — the resume
        admission re-prefills only the uncached tail."""
        eng = self.engine
        entry = self._active.pop(slot)
        self.tracer.end(entry.idx, "decode",
                        new_tokens=len(entry.seg_tokens))
        self.tracer.begin(entry.idx, "preempted")
        self.tracer.event(entry.idx, "preempt", slot=slot,
                          generated=entry.generated)
        self._C["preemptions"].inc()
        eng.events.emit("preempt", request=entry.idx, slot=slot,
                        generated=entry.generated)
        self._release_pages(slot, entry)
        self._pool_dirty = True
        self._done = self._done.at[slot].set(True)
        # fold the segment into the entry: the resume prompt carries
        # every generated token (incl. the never-written last one — its
        # K/V re-prefills), the budget shrinks by what was delivered
        entry.prompt = np.concatenate(
            [entry.prompt, np.asarray(entry.seg_tokens, np.int32)])
        entry.prev = entry.prev + entry.seg_tokens
        entry.seg_tokens = []
        entry.nodes = []
        entry.resume = True
        self._pending.append(entry)

    # --- consumption-aware backpressure (docs/http.md) ----------------------

    def _bp_stalled(self, entry: _Entry) -> bool:
        """An ACTIVE slot whose consumer is stalled past the window —
        a backpressure-spill victim. Cancelled handles are excluded
        (harvest retires them; their pages free anyway)."""
        w = self.backpressure_window
        return (w is not None and not entry.prefilling
                and not entry.handle.cancelled
                and entry.handle.unread() > w)

    def _bp_held(self, entry: _Entry) -> bool:
        """A PENDING entry whose consumer is still behind — held out of
        admission (re-admitting would spill again next boundary).
        Hysteresis: released once unread falls to half the window, so a
        resumed slot gets a full half-window of runway. Cancelled
        entries are never held (admission finishes them)."""
        w = self.backpressure_window
        return (w is not None and entry.resume
                and not entry.handle.cancelled
                and entry.handle.unread() > w // 2)

    def _backpressure_spill(self) -> None:
        """Spill every active slot whose reader stalled past the
        in-flight-token window through the PREEMPTION path: flush the
        pipeline, release the slot's full pages into the radix cache
        (partial tail frees), requeue the entry for resume-on-
        consumption. This bypasses the policy's ``wants_preempt`` gate —
        the victim is not losing its slot to a more urgent request, it
        is refusing to pin pool pages behind a dead socket."""
        if self.backpressure_window is None or not self._active:
            return
        victims = [s for s, e in self._active.items()
                   if self._bp_stalled(e)]
        if not victims:
            return
        self._flush()                    # victim state must be current
        for slot in victims:
            entry = self._active.get(slot)
            if entry is None or not self._bp_stalled(entry):
                continue                 # the flush retired/changed it
            self._C["backpressure_spills"].inc()
            self.tracer.event(entry.idx, "backpressure_spill",
                              slot=slot, unread=entry.handle.unread())
            self.engine.events.emit("backpressure_spill",
                                    request=entry.idx, slot=slot,
                                    unread=entry.handle.unread())
            self._preempt(slot)

    def _maybe_preempt(self, candidate: _Entry, now: float) -> bool:
        """Try to free a slot (and spill pages) for a blocked
        ``candidate``. True when the boundary state changed (a victim
        was preempted, or the flush itself retired slots) — the caller
        retries the candidate's admission."""
        eng = self.engine
        if not self.policy.wants_preempt(candidate, now):
            return False
        # a candidate the whole pool cannot hold is a deadlock, not a
        # preemption target — don't kill running work for it
        need_total = kv_pool.pages_for(candidate.s0 + candidate.seg_new,
                                       eng.page_size)
        if need_total > kv_pool.num_pages_of(eng.cache) - 1:
            return False
        # a mid-prefill slot has emitted nothing and holds no decode
        # state to fold back — never a preemption victim
        decoding = {s: e for s, e in self._active.items()
                    if not e.prefilling}
        victim_slot = self.policy.select_victim(candidate, decoding, now)
        if victim_slot is None:
            return False
        n_active = len(self._active)
        self._flush()                    # victim state must be current
        if victim_slot not in self._active:
            return True                  # the flush retired it — retry
        if len(self._active) < n_active and any(
                s not in self._active for s in range(eng.num_slots)):
            return True                  # flush freed another slot
        self._preempt(victim_slot)
        return True

    # --- tiered pool (docs/serving.md "Tiered KV pool") ---------------------

    def _demote(self, victims) -> None:
        """Dispatch the device->host gather of evicted pages about to be
        pushed onto the free stack: ``victims`` is the eviction sink's
        ``(path_keys, page)`` list. Each ``HOST_COPY_CHUNK`` batch is one
        async ``gather_pages`` call (null-padded row — depth is data);
        the tiles land in the tier as PENDING device arrays and convert
        to host entries at the pump's next host-work slot."""
        eng = self.engine
        C = kv_pool.HOST_COPY_CHUNK
        for i in range(0, len(victims), C):
            grp = victims[i:i + C]
            row = np.zeros((C,), np.int32)
            row[:len(grp)] = [page for _, page in grp]
            tiles = eng._gather_jit(eng.cache, jnp.asarray(row))
            eng.host_tier.put_pending([path for path, _ in grp], tiles,
                                      n=len(grp))

    def _try_promote(self, entry: _Entry, nodes: list) -> list:
        """Extend ``entry``'s tree match with consecutive host-resident
        pages: scatter their demoted bytes into freshly popped pages
        (``kv_pool.promote_pages`` — bit-stable, never a re-prefill) and
        graft them into the radix tree, returning the extended node path
        for the ordinary shared admission. The match FLOOR is computed
        first (a resume matches at its exact written depth, a cold
        admission at the power-of-two bucket) so only pages that survive
        the floor promote — a promoted-then-floored page would be a
        wasted copy. When the free stack cannot cover both the promoted
        pages and the admission's remaining private need, the tier
        SWAPS: refcount-0 LRU pages evict (demoting through the same
        sink — the matched path is pinned around the walk so the LRU
        cannot eat it) to make room. If eviction still leaves the stack
        short, the promotion skips (tier entries untouched) and the
        admission proceeds as if the tier had missed."""
        eng = self.engine
        tier = eng.host_tier
        ps = eng.page_size
        prompt, s0 = entry.prompt, entry.s0
        tier.drain()                     # pending demotes become hits
        floor = (lambda d: d) if entry.resume else _bucket_match_pages
        m0 = len(nodes)
        cap = max(s0 - 1, 0) // ps       # match()'s own depth cap
        if m0 >= cap:
            return nodes[:floor(m0)]
        base = tuple(n.key for n in nodes)
        keys = [tuple(int(t) for t in prompt[j * ps:(j + 1) * ps])
                for j in range(m0, cap)]
        r = tier.run_length(base, keys)
        target = floor(m0 + r)
        if target <= m0:
            return nodes[:target]
        h = target - m0
        # the pool read below syncs the stream — stamp the in-flight
        # chunk first (same discipline as the admission's free read)
        if self._inflight is not None:
            self._materialize(self._inflight)
        free = int(kv_pool.free_page_count(eng.cache))
        need_after = kv_pool.pages_for(s0 + entry.seg_new, ps) - target
        if free < h + need_after:
            # the tier swap: in a thrashing pool the stack is never
            # free-handed, so evict cold refcount-0 pages (they demote
            # through the same sink) to make room for the hot ones. Pin
            # the matched path first — it is not acquired yet, and the
            # LRU walk must not evict it out from under the promotion.
            eng.prefix.acquire(nodes)
            victims: List[tuple] = []
            pages = eng.prefix.evict(
                h + need_after - free,
                sink=lambda path_, page: victims.append((path_, page)))
            eng.prefix.release(nodes)
            if victims:
                self._demote(victims)
            if pages:
                max_pages = eng.cache["block_tables"].shape[1]
                row = np.zeros((max_pages,), np.int32)
                row[:len(pages)] = pages
                eng.cache = eng._evict_jit(eng.cache, jnp.asarray(row),
                                           jnp.int32(len(pages)))
                self._C["evicted_pages"].inc(len(pages))
                eng.events.emit("evict", request=entry.idx,
                                pages=len(pages))
                free += len(pages)
            if free < h + need_after:
                return nodes[:floor(m0)]
        payloads = []
        path = base
        for i in range(h):
            path = path + (keys[i],)
            payloads.append(tier.pop(path))  # ownership: tier -> pool
        # destinations: the top h free-stack entries, host-read in the
        # same pop order alloc_slot uses — promote_pages decrements
        # free_top by exactly these pages
        stack = np.asarray(eng.cache["free_stack"])
        page_ids = stack[free - h:free][::-1].astype(np.int32)
        C = kv_pool.HOST_COPY_CHUNK
        t0 = self.clock()
        for i in range(0, h, C):
            n_g = min(C, h - i)
            row = np.zeros((C,), np.int32)
            row[:n_g] = page_ids[i:i + n_g]
            eng.cache = eng._promote_jit(
                eng.cache, jnp.asarray(row), jnp.int32(n_g),
                _stack_tiles(payloads[i:i + n_g], C))
        # block on the promoted pool's scalar: the measured span is the
        # host->device copy the admission program would wait on anyway
        np.asarray(eng.cache["free_top"])
        tier.observe_promote_ms((self.clock() - t0) * 1e3)
        for i in range(h):
            nodes.append(eng.prefix.insert_promoted(nodes, keys[i],
                                                    int(page_ids[i])))
        self.tracer.event(entry.idx, "promote", pages=h)
        eng.events.emit("promote", request=entry.idx, pages=h)
        self._pool_dirty = True
        return nodes

    # --- admission ----------------------------------------------------------

    def _try_admit(self, entry: _Entry, slot: int, now: float) -> bool:
        """Admit ``entry`` into vacant ``slot`` if the pool can hold it
        (evicting/defragging as needed); False defers it (head-of-line:
        the caller stops the admission pass). Mirrors the engine's
        original admission exactly, plus the resume path: a resume's
        prefix match is NOT floored to a power of two pages — its depth
        is its own written length (already page-quantized), so the full
        spilled prefix is reused and only the ≤ one-page tail
        re-prefills."""
        eng = self.engine
        tr = self.tracer
        cfg, ps = eng.cfg, eng.page_size
        max_pages = eng.cache["block_tables"].shape[1]
        prompt, s0, idx = entry.prompt, entry.s0, entry.idx
        need_total = kv_pool.pages_for(s0 + entry.seg_new, ps)
        # prefix match BEFORE the page check: matched pages are shared,
        # not allocated, so they shrink the demand. Acquire immediately —
        # eviction below must see them pinned, not as LRU victims
        nodes = eng.prefix.match(prompt) if eng.prefix is not None else []
        if eng.host_tier is not None:
            # tiered pool: extend the tree match with host-resident
            # pages (promote instead of re-prefill); applies the match
            # floor itself, so the plain floor below is the tier-off path
            nodes = self._try_promote(entry, nodes)
        elif not entry.resume:
            nodes = nodes[:_bucket_match_pages(len(nodes))]
        if nodes:
            eng.prefix.acquire(nodes)
        m = len(nodes)
        need = need_total - m
        # the pool read below waits for everything queued on the stream —
        # including the in-flight chunk; stamp its completion FIRST so
        # decode_step_ms never charges admission work to the chunk
        if self._inflight is not None:
            self._materialize(self._inflight)
        free = int(kv_pool.free_page_count(eng.cache))
        if free < need and eng.prefix is not None:
            victims: List[tuple] = []
            sink = ((lambda path, page: victims.append((path, page)))
                    if eng.host_tier is not None else None)
            pages = eng.prefix.evict(need - free, sink=sink)
            if victims:
                # demote BEFORE the stack push: the gather is queued on
                # the device stream ahead of any program that could
                # re-allocate (and overwrite) the evicted pages
                self._demote(victims)
            if pages:
                row = np.zeros((max_pages,), np.int32)
                row[:len(pages)] = pages
                eng.cache = eng._evict_jit(eng.cache, jnp.asarray(row),
                                           jnp.int32(len(pages)))
                self._C["evicted_pages"].inc(len(pages))
                eng.events.emit("evict", request=idx, pages=len(pages))
                free += len(pages)
        if free < need and eng._leak_suspected(free, self._active):
            eng._defrag_now()
            self._C["defrag_runs"].inc()
            eng.events.emit("defrag", request=idx)
            free = int(kv_pool.free_page_count(eng.cache))
        if free < need:
            if nodes:
                eng.prefix.release(nodes)
            self._C["deferred_admissions"].inc()
            eng.events.emit("defer", request=idx, need_pages=need,
                            free_pages=free)
            return False
        if entry.resume:
            tr.end(idx, "preempted")
            tr.event(idx, "resume", slot=slot, cached_pages=m,
                     resumed_at=entry.generated)
            self._C["resumes"].inc()
            eng.events.emit("resume", request=idx, slot=slot,
                            cached_pages=m)
        tr.event(idx, "admit", slot=slot, free_pages=free, cached_pages=m)
        req_key = jax.random.fold_in(eng.rng, idx)
        samp0 = len(entry.prev)          # resume continues the key stream
        # chunked prefill (docs/frontend.md): instead of one monolithic
        # contiguous prefill, allocate the pages now and feed the
        # uncached tail through the paged s>1 path one
        # ``prefill_chunk``-token piece per pump iteration, interleaved
        # with decode chunks — a long prompt never blocks the running
        # slots' next decode step. Short tails (<= one chunk) keep the
        # monolithic path: one program call either way.
        if (eng.prefill_chunk is not None and s0 - m * ps > eng.prefill_chunk
                and s0 + eng.prefill_chunk - 1 <= max_pages * ps):
            tr.begin(idx, "prefill", cached_tokens=m * ps,
                     computed_tokens=s0 - m * ps, chunked=True)
            if m == 0:
                eng.cache = eng._chunk_alloc_jit(
                    eng.cache, jnp.int32(slot), jnp.int32(need))
            else:
                self._C["prefix_hits"].inc()
                row = np.zeros((max_pages,), np.int32)
                row[:m] = [n.page for n in nodes]
                eng.cache = eng._chunk_alloc_shared_jit(
                    eng.cache, jnp.int32(slot), jnp.asarray(row),
                    jnp.int32(m), jnp.int32(need))
            self._C["admitted"].inc()
            self._C["chunked_prefills"].inc()
            self._C["prefill_tokens_total"].inc(s0)
            self._C["prefill_tokens_computed"].inc(s0 - m * ps)
            eng.events.emit("admit", request=idx, slot=slot,
                            prompt_tokens=s0, cached_tokens=m * ps,
                            priority=entry.priority, chunked=True)
            entry.nodes = nodes
            entry.n_private = need
            entry.win_dropped = 0
            entry.seg_tokens = []
            entry.prefilling = True
            entry.pf_pos = m * ps
            entry.pf_key = req_key
            entry.pf_samp0 = samp0
            # no harvestable decode tokens until the prefill finishes
            entry.joined = self._chunk + (1 << 30)
            self._active[slot] = entry
            self._pool_dirty = True
            self._feed_chunk(slot, entry)    # first chunk rides now
            return True
        # prefill span: covers the admission program AND the first-token
        # sync — its end IS the first token's arrival
        with tr.span(idx, "prefill", cached_tokens=m * ps,
                     computed_tokens=s0 - m * ps):
            if m == 0:
                admit_fn, bucket = self.admission_program(s0)
                ids = np.zeros((1, bucket), np.int32)
                ids[0, :s0] = prompt
                if eng.draft_len:
                    # speculative admission prefills the draft pool too
                    eng.cache, eng.draft_cache, tok0 = admit_fn(
                        eng.cache, eng.draft_cache, eng.variables,
                        eng.draft_variables, jnp.asarray(ids),
                        jnp.int32(s0), jnp.int32(slot), jnp.int32(need),
                        req_key, jnp.int32(samp0))
                else:
                    eng.cache, tok0 = admit_fn(
                        eng.cache, eng.variables, jnp.asarray(ids),
                        jnp.int32(s0), jnp.int32(slot), jnp.int32(need),
                        req_key, jnp.int32(samp0))
            else:
                self._C["prefix_hits"].inc()
                t_start = m * ps
                tail_bucket = min(round_up(s0 - t_start, ps),
                                  cfg.max_position_embeddings - t_start)
                ids = np.zeros((1, tail_bucket), np.int32)
                ids[0, :s0 - t_start] = prompt[t_start:]
                row = np.zeros((max_pages,), np.int32)
                row[:m] = [n.page for n in nodes]
                eng.cache, tok0 = eng._admit_shared_fn(
                    t_start, tail_bucket)(
                    eng.cache, eng.variables, jnp.asarray(ids),
                    jnp.int32(s0), jnp.int32(slot), jnp.asarray(row),
                    jnp.int32(need), req_key, jnp.int32(samp0))
            tok0 = int(tok0)
        if not entry.first_token_seen:
            entry.first_token_seen = True
            tr.event(idx, "first_token", slot=slot)
            # the TTFT SLO check, exactly once per request — a resume's
            # re-admission never re-counts
            if (entry.deadline_at is not None
                    and self.clock() > entry.deadline_at):
                entry.deadline_missed = True
                self._C["deadline_misses"].inc()
                tr.event(idx, "deadline_miss")
                eng.events.emit("deadline_miss", request=idx)
        tr.begin(idx, "decode", slot=slot)
        self._C["admitted"].inc()
        self._C["prefill_tokens_total"].inc(s0)
        self._C["prefill_tokens_computed"].inc(s0 - m * ps)
        eng.events.emit("admit", request=idx, slot=slot, prompt_tokens=s0,
                        cached_tokens=m * ps, priority=entry.priority)
        entry.nodes = nodes
        entry.n_private = need
        entry.win_dropped = 0            # fresh row: nothing dropped yet
        entry.seg_tokens = [tok0]
        entry.joined = self._chunk + 1
        self._active[slot] = entry
        entry.handle._push(tok0)
        self._pool_dirty = True
        if ((eng.eos_token_id is not None and tok0 == eng.eos_token_id)
                or entry.seg_new == 1):
            self._retire(slot)
            return True
        self._tok = self._tok.at[slot].set(tok0)
        self._done = self._done.at[slot].set(False)
        self._n_left = self._n_left.at[slot].set(entry.seg_new - 1)
        self._samp_i = self._samp_i.at[slot].set(samp0 + 1)
        self._req_keys = self._req_keys.at[slot].set(req_key)
        return True

    def _advance_prefills(self) -> bool:
        """Feed ONE ``prefill_chunk``-token chunk to every mid-prefill
        slot (an async dispatch each — interleaved on the device stream
        with the in-flight decode chunk); finish the ones whose prompt
        is exhausted into decoding slots. True when any slot advanced."""
        if self.engine.prefill_chunk is None:
            return False
        advanced = False
        for slot in list(self._active):
            entry = self._active.get(slot)
            if entry is None or not entry.prefilling:
                continue
            if entry.handle.cancelled:
                self._abort_prefill(slot, entry)
                continue
            self._feed_chunk(slot, entry)
            advanced = True
        return advanced

    def _feed_chunk(self, slot: int, entry: _Entry) -> None:
        eng = self.engine
        C = eng.prefill_chunk
        t, s0 = entry.pf_pos, entry.s0
        valid = min(C, s0 - t)
        ids = np.zeros((1, C), np.int32)     # final chunk zero-pads
        ids[0, :valid] = entry.prompt[t:t + valid]
        eng.cache, tok = eng._prefill_chunk_fn()(
            eng.cache, eng.variables, jnp.asarray(ids), jnp.int32(slot),
            jnp.int32(valid), entry.pf_key, jnp.int32(entry.pf_samp0))
        entry.pf_pos = t + valid
        self._C["prefill_chunks"].inc()
        if entry.pf_pos >= s0:
            # the first-token sync below waits on the whole stream —
            # stamp the in-flight decode chunk's completion first so
            # decode_step_ms never charges prefill work to it
            if self._inflight is not None:
                self._materialize(self._inflight)
            self._finish_prefill(slot, entry, int(tok))

    def _finish_prefill(self, slot: int, entry: _Entry, tok0: int) -> None:
        """The prompt's final chunk landed: sample arrived (``tok0`` off
        the last valid logit), so run the same post-admission wiring the
        monolithic path does and hand the slot to the decode chunk."""
        eng = self.engine
        tr = self.tracer
        idx = entry.idx
        entry.prefilling = False
        tr.end(idx, "prefill")
        if not entry.first_token_seen:
            entry.first_token_seen = True
            tr.event(idx, "first_token", slot=slot)
            if (entry.deadline_at is not None
                    and self.clock() > entry.deadline_at):
                entry.deadline_missed = True
                self._C["deadline_misses"].inc()
                tr.event(idx, "deadline_miss")
                eng.events.emit("deadline_miss", request=idx)
        tr.begin(idx, "decode", slot=slot)
        entry.seg_tokens = [tok0]
        entry.joined = self._chunk + 1
        entry.handle._push(tok0)
        self._pool_dirty = True
        if ((eng.eos_token_id is not None and tok0 == eng.eos_token_id)
                or entry.seg_new == 1):
            self._retire(slot)
            return
        self._tok = self._tok.at[slot].set(tok0)
        self._done = self._done.at[slot].set(False)
        self._n_left = self._n_left.at[slot].set(entry.seg_new - 1)
        self._samp_i = self._samp_i.at[slot].set(entry.pf_samp0 + 1)
        self._req_keys = self._req_keys.at[slot].set(entry.pf_key)

    def _abort_prefill(self, slot: int, entry: _Entry) -> None:
        """Cancellation mid-prefill: no decode state exists — release
        the pages (full fed pages still cacheable) and finish the handle
        with the earlier segments' tokens."""
        eng = self.engine
        self._active.pop(slot)
        self._C["retired"].inc()
        self.tracer.end(entry.idx, "prefill")
        self.tracer.event(entry.idx, "retire", slot=slot,
                          new_tokens=len(entry.prev), cancelled=True)
        eng.events.emit("cancel", request=entry.idx, slot=slot,
                        new_tokens=len(entry.prev))
        self._release_pages(slot, entry)
        self._pool_dirty = True
        entry.handle._finish(np.asarray(entry.prev, np.int32))

    def _admission(self) -> int:
        """Fill vacant slots from the policy-ordered pending queue;
        preempt for the head when the policy demands it. Head-of-line
        blocking is preserved inside the order: if the most urgent
        pending request cannot get pages, nothing behind it jumps the
        queue (the engine's original FIFO fairness, generalized to the
        policy order)."""
        eng = self.engine
        now = self.clock()
        held: List[_Entry] = []
        if self.backpressure_window is not None and self._pending:
            # backpressure-held entries sit out this admission pass
            # entirely (they are waiting on their CONSUMER, not on
            # slots/pages) — and must not head-of-line-block the queue
            held = [e for e in self._pending if self._bp_held(e)]
            if held:
                held_ids = {id(e) for e in held}
                self._pending = [e for e in self._pending
                                 if id(e) not in held_ids]
        self._pending.sort(key=lambda e: self.policy.sort_key(e, now))
        admitted = 0
        preempts_left = eng.num_slots    # bound the preempt-retry loop
        while self._pending:
            entry = self._pending[0]
            if entry.handle.cancelled:
                self._pending.pop(0)
                eng.events.emit("cancel", request=entry.idx, queued=True)
                entry.handle._finish(
                    np.asarray(entry.prev, np.int32))
                continue
            free_slots = [s for s in range(eng.num_slots)
                          if s not in self._active]
            if not free_slots:
                if preempts_left > 0 and self._maybe_preempt(entry, now):
                    preempts_left -= 1
                    continue
                break
            if self._try_admit(entry, free_slots[0], now):
                self._pending.pop(0)
                admitted += 1
                continue
            # page-short: preemption can spill a lower-priority slot's
            # pages (they become evictable cached pages) — retry once
            # per victim, then defer head-of-line
            if preempts_left > 0 and self._maybe_preempt(entry, now):
                preempts_left -= 1
                continue
            break
        self._pending.extend(held)
        return admitted

    # --- recompile storm check ----------------------------------------------

    def _check_compile_storm(self) -> None:
        """Warn (once per function name, into the engine's postmortem
        ring) when one program recompiled storm-many times within this
        frontend's lifetime — a recompile inside the pump is a serving
        latency cliff the IR tier's cardinality lint can only bound
        statically (docs/observability.md)."""
        storms = self._watch.storms(
            self._jit0, threshold=compile_watch.DEFAULT_STORM_THRESHOLD)
        for name, n in storms.items():
            if name not in self._storm_seen:
                self._storm_seen.add(name)
                self.engine.events.emit("compile_storm", fn=name,
                                        compiles=n)

    # --- run-scoped stats ---------------------------------------------------

    def counter_deltas(self) -> Dict[str, float]:
        """This frontend's ``serving.*`` counter deltas since
        construction — the raw numbers ``stats()`` derives its view
        from, WITHOUT recording anything (safe to poll; the router's
        aggregate stats read replicas through this)."""
        return {name: c.value - self._c0[name]
                for name, c in self._C.items()}

    def stats(self) -> dict:
        """The engine-stats dict for this frontend's lifetime so far —
        counter deltas since construction plus run-local latency
        percentiles (the same shape ``engine.run()`` has always
        returned, grown by the frontend counters). Records every numeric
        stat as a ``serving.<name>`` raw series — call once per run."""
        eng = self.engine
        d = self.counter_deltas()
        with self._ingest_lock:      # peak is written under this lock
            peak_queue_depth = self.peak_queue_depth
        stats = {
            "decode_steps": int(d["decode_steps"]),
            "admitted": int(d["admitted"]),
            "retired": int(d["retired"]),
            "peak_slots_in_use": self.peak_slots,
            "slot_occupancy": (d["busy_slot_steps"]
                               / max(d["decode_steps"] * eng.num_slots,
                                     1)),
            "deferred_admissions": int(d["deferred_admissions"]),
            "defrag_runs": int(d["defrag_runs"]),
            # chips the engine's programs span (serving/tp.py) — 1 for
            # the single-chip engine; per-chip throughput = aggregate /
            # tp_world (the pool/weight shards each chip streams)
            "tp_world": int(getattr(eng, "tp_world", 1)),
            "preemptions": int(d["preemptions"]),
            "resumes": int(d["resumes"]),
            "backpressure_spills": int(d["backpressure_spills"]),
            "deadline_misses": int(d["deadline_misses"]),
            "tpot_slo_misses": int(d["tpot_slo_misses"]),
            "window_dropped_pages": int(d["window_dropped_pages"]),
            "slo_burn": self._slo_burn.value,
            "peak_queue_depth": peak_queue_depth,
            "prefix_cache_enabled": eng.prefix is not None,
            "prefix_hits": int(d["prefix_hits"]),
            "prefix_hit_rate": d["prefix_hits"] / max(d["admitted"], 1),
            "prefix_cached_pages": (len(eng.prefix)
                                    if eng.prefix is not None else 0),
            "evicted_pages": int(d["evicted_pages"]),
            "prefill_tokens_total": int(d["prefill_tokens_total"]),
            "prefill_tokens_computed": int(d["prefill_tokens_computed"]),
            "prefill_tokens_skipped": int(d["prefill_tokens_total"]
                                          - d["prefill_tokens_computed"]),
            # speculative decode: emitted tokens per verify round (1..k;
            # > 1 means the draft is paying for itself)
            "spec_rounds": int(d["spec_rounds"]),
            "spec_tokens": int(d["spec_tokens"]),
            "mean_acceptance_len": (d["spec_tokens"]
                                    / max(d["spec_rounds"], 1)),
            "chunked_prefills": int(d["chunked_prefills"]),
            "prefill_chunks": int(d["prefill_chunks"]),
        }
        # tiered pool (docs/serving.md "Tiered KV pool"): lifetime
        # demote/promote totals + the promote-hit rate, pool.host_tier_*
        # instruments' stats()-shape view
        stats["host_tier_enabled"] = eng.host_tier is not None
        if eng.host_tier is not None:
            stats.update(eng.host_tier.stats())
        # pump pipeline attribution + the recompile window
        # (docs/frontend.md "Measuring the pump"): bubble is the mean
        # device-idle gap per handoff — ~0 when double-buffering hides
        # the host work
        bubbles = self._per_run["pump.bubble_ms"]
        stats["pump.bubble_ms"] = float(np.mean(bubbles)) if bubbles \
            else 0.0
        compiles, trace_misses = self._watch.totals()
        stats["jit.compiles"] = compiles - self._jit_totals0[0]
        stats["jit.trace_cache_misses"] = \
            trace_misses - self._jit_totals0[1]
        # storm-many recompiles of one program within this frontend's
        # lifetime (the preemption-storm scenario pins this at 0: the
        # resume compile-key set must stay bounded)
        stats["compile_storms"] = len(self._storm_seen)
        # run-local latency percentiles (the global histograms hold the
        # engine-lifetime distributions; these are exact per run)
        for name, vals in self._per_run.items():
            if vals:
                stats[f"{name}_p50"] = float(np.percentile(vals, 50))
                stats[f"{name}_p95"] = float(np.percentile(vals, 95))
        for name, val in stats.items():
            if isinstance(val, bool):
                continue
            metrics.record(f"serving.{name}", val)
        return stats
