"""Paged KV pool: static page-granular cache storage + block tables.

Layout (the PAGED cache pytree — a drop-in ``cache=`` argument for the
models' incremental-decode path, recognized by its ``block_tables`` key):

    pcache = {
      "layers": [{"k_pages": (num_pages, kv_local, page_size, d),
                  "v_pages": ...}] * num_layers,
      "block_tables": (num_slots, max_pages_per_seq) int32,
      "len":          (num_slots,) int32   # tokens written per slot
      "alloc_pages":  (num_slots,) int32,  # pages OWNED per slot
      "free_stack":   (num_pages,) int32,  # stack[0:free_top] = free pages
      "free_top":     () int32,
    }

``alloc_pages`` tracks ownership, not occupancy: the scheduler allocates a
request's worst case (``ceil((prompt+max_new)/page_size)``) up front, so a
slot owns pages its length has not reached yet — free/defrag must treat
those as live (freeing by ``ceil(len/page_size)`` would leak the tail).

Page 0 is the reserved NULL page: never allocated, and every dead block
table entry (idle slot, tail of a short sequence) points at it, so index
maps and masked writes always resolve to a valid page — static shapes,
no bounds branches. It is a SINK, not untouched storage: idle/done slots
write their fill tokens' K/V there and attend over it (outputs masked or
discarded) — no LIVE sequence ever reads it, but its contents are
arbitrary finite garbage, so never repurpose it as zeroed or poisonable
storage. The free list is a fixed-size int32 stack; alloc pops
``n`` pages off the top with a masked gather, free pushes them back with
a masked ``mode="drop"`` scatter — both jittable at one shape forever
(the ``n`` is a traced scalar, the mask is what varies).

The lane-alignment discipline mirrors ``ops/flat_buffer.py``: a page tile
is ``(page_size, head_dim)``, so ``page_size`` must be a sublane multiple
(8) and should be >= 16 for bf16 pools.
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp

from apex_tpu.amp.policy import resolve_compute_dtype
from apex_tpu.ops._dispatch import cdiv
from apex_tpu.transformer.utils import divide


def page_size_of(cache) -> int:
    return cache["layers"][0]["k_pages"].shape[2]


def num_pages_of(cache) -> int:
    return cache["layers"][0]["k_pages"].shape[0]


def pages_for(length, page_size: int):
    """Pages needed for ``length`` tokens (traced or static)."""
    if isinstance(length, int):
        return cdiv(length, page_size)
    return (length + page_size - 1) // page_size


def init_paged_cache(config, num_slots: int, *, num_pages: int,
                     page_size: int = 16,
                     max_pages_per_seq: Optional[int] = None, dtype=None):
    """Allocate the shared page pool + empty slot state.

    ``num_pages`` includes the reserved null page 0, so the usable
    capacity is ``(num_pages - 1) * page_size`` tokens across all
    in-flight sequences. ``max_pages_per_seq`` bounds one sequence's block
    table (default: enough for ``max_position_embeddings``)."""
    if page_size % 8 != 0:
        raise ValueError(f"page_size must be a sublane multiple (8), got "
                         f"{page_size}")
    if num_pages < 2:
        raise ValueError("num_pages must be >= 2 (page 0 is reserved)")
    kv_heads = getattr(config, "num_kv_heads", config.num_heads)
    kv_local = divide(kv_heads, config.tensor_parallel_size)
    d = config.head_dim
    dt = dtype if dtype is not None else resolve_compute_dtype(config.dtype)
    if max_pages_per_seq is None:
        max_pages_per_seq = cdiv(config.max_position_embeddings, page_size)
    shape = (num_pages, kv_local, page_size, d)
    layers = [{"k_pages": jnp.zeros(shape, dt), "v_pages": jnp.zeros(shape, dt)}
              for _ in range(config.num_layers)]
    return {
        "layers": layers,
        "block_tables": jnp.zeros((num_slots, max_pages_per_seq), jnp.int32),
        "len": jnp.zeros((num_slots,), jnp.int32),
        "alloc_pages": jnp.zeros((num_slots,), jnp.int32),
        # pages 1..num_pages-1 free; popped from the top of the stack
        "free_stack": jnp.arange(1, num_pages + 1, dtype=jnp.int32
                                 ) % num_pages,
        "free_top": jnp.asarray(num_pages - 1, jnp.int32),
    }


def free_page_count(cache):
    return cache["free_top"]


def alloc_slot(cache, slot, n_pages):
    """Pop ``n_pages`` pages off the free stack and install them as slot
    ``slot``'s block table row (entries past ``n_pages`` point at the null
    page). ``slot``/``n_pages`` may be traced. The CALLER must ensure
    ``free_page_count(cache) >= n_pages`` (the scheduler's admission
    check) — the stack read clamps, so an over-alloc would silently hand
    out duplicate pages."""
    bt, stack, top = (cache["block_tables"], cache["free_stack"],
                      cache["free_top"])
    max_pages = bt.shape[1]
    num_pages = stack.shape[0]
    idx = jnp.arange(max_pages, dtype=jnp.int32)
    take = idx < n_pages
    src = jnp.clip(top - 1 - idx, 0, num_pages - 1)
    row = jnp.where(take, stack[src], 0)
    out = dict(cache)
    out["free_top"] = top - jnp.asarray(n_pages, jnp.int32)
    out["block_tables"] = bt.at[slot].set(row)
    out["alloc_pages"] = cache["alloc_pages"].at[slot].set(
        jnp.asarray(n_pages, jnp.int32))
    return out


def free_slot(cache, slot):
    """Retire slot ``slot``: push ALL its owned pages (``alloc_pages``,
    not just the length-covered prefix) back onto the free stack, reset
    its block table row to the null page, and zero its length."""
    bt, stack, top = (cache["block_tables"], cache["free_stack"],
                      cache["free_top"])
    max_pages = bt.shape[1]
    num_pages = stack.shape[0]
    row = bt[slot]
    n = cache["alloc_pages"][slot]
    idx = jnp.arange(max_pages, dtype=jnp.int32)
    take = idx < n
    dst = jnp.where(take, top + idx, num_pages)      # OOB -> dropped
    out = dict(cache)
    out["free_stack"] = stack.at[dst].set(row, mode="drop")
    out["free_top"] = top + n.astype(jnp.int32)
    out["block_tables"] = bt.at[slot].set(jnp.zeros((max_pages,), jnp.int32))
    out["len"] = cache["len"].at[slot].set(0)
    out["alloc_pages"] = cache["alloc_pages"].at[slot].set(0)
    return out


def defrag(cache):
    """Compact live pages to the low end of the pool (stable order) and
    rebuild the free stack from actual liveness.

    With a block-table indirection fragmentation never costs correctness
    or speed — any free page is as good as another — but compaction keeps
    the live set prefix-dense (cheap pool-prefix checkpointing / shrink)
    and doubles as a leak collector: a page reachable from no slot's table
    returns to the free stack even if an earlier free miscounted. O(pool)
    gather per layer — an explicit maintenance op, not a per-step one."""
    bt = cache["block_tables"]
    num_pages = num_pages_of(cache)
    max_pages = bt.shape[1]

    # liveness bound = OWNED pages (a slot's preallocated-but-unwritten
    # tail is live: its future tokens land there)
    used_entries = (jnp.arange(max_pages, dtype=jnp.int32)[None, :]
                    < cache["alloc_pages"][:, None])         # (slots, mp)
    live = jnp.zeros((num_pages,), bool).at[
        jnp.where(used_entries, bt, 0)].set(True)
    live = live.at[0].set(True)                  # null page stays page 0
    n_live = jnp.sum(live.astype(jnp.int32))
    new_idx = jnp.where(live, jnp.cumsum(live.astype(jnp.int32)) - 1,
                        n_live + jnp.cumsum((~live).astype(jnp.int32)) - 1
                        ).astype(jnp.int32)
    old_of_new = jnp.zeros((num_pages,), jnp.int32).at[new_idx].set(
        jnp.arange(num_pages, dtype=jnp.int32))

    out = dict(cache)
    out["layers"] = [{"k_pages": lc["k_pages"][old_of_new],
                      "v_pages": lc["v_pages"][old_of_new]}
                     for lc in cache["layers"]]
    out["block_tables"] = jnp.where(used_entries, new_idx[bt], 0)
    idx = jnp.arange(num_pages, dtype=jnp.int32)
    out["free_stack"] = jnp.where(idx < num_pages - n_live, n_live + idx, 0)
    out["free_top"] = (num_pages - n_live).astype(jnp.int32)
    return out


def prefill_into_pages(cache, slot, contig_layers, s0):
    """Scatter a CONTIGUOUS prefill cache (the models' flash-prefill
    output: per-layer ``k``/``v`` of shape ``(1, kv, len_bucket, d)``)
    into slot ``slot``'s already-allocated pages, and set its length to
    ``s0`` (traced OK; positions past ``s0`` — prompt-bucket padding —
    scatter to the null page). Position ``p`` lands in table entry
    ``p // page_size`` at offset ``p % page_size``."""
    bt = cache["block_tables"]
    ps = page_size_of(cache)
    max_pages = bt.shape[1]
    len_bucket = contig_layers[0]["k"].shape[2]
    pos = jnp.arange(len_bucket, dtype=jnp.int32)
    valid = pos < s0
    row = bt[slot]
    phys = jnp.where(valid, row[jnp.clip(pos // ps, 0, max_pages - 1)], 0)
    off = pos % ps

    out = dict(cache)
    new_layers = []
    for lc, src in zip(cache["layers"], contig_layers):
        k = src["k"][0].transpose(1, 0, 2)       # (len_bucket, kv, d)
        v = src["v"][0].transpose(1, 0, 2)
        new_layers.append({
            "k_pages": lc["k_pages"].at[phys, :, off, :].set(
                k.astype(lc["k_pages"].dtype)),
            "v_pages": lc["v_pages"].at[phys, :, off, :].set(
                v.astype(lc["v_pages"].dtype)),
        })
    out["layers"] = new_layers
    out["len"] = cache["len"].at[slot].set(jnp.asarray(s0, jnp.int32))
    return out
