"""Paged KV pool: static page-granular cache storage + block tables.

Layout (the PAGED cache pytree — a drop-in ``cache=`` argument for the
models' incremental-decode path, recognized by its ``block_tables`` key):

    pcache = {
      "layers": [{"k_pages": (num_pages, kv_local, page_size, d),
                  "v_pages": ...,
                  # quantized pools only (init_paged_cache(kv_dtype=)):
                  "k_scales": (num_pages, kv_local) f32, "v_scales": ...}]
                * num_layers,
      "block_tables": (num_slots, max_pages_per_seq) int32,
      "len":          (num_slots,) int32   # tokens written per slot
      "alloc_pages":  (num_slots,) int32,  # pages OWNED per slot
      "shared_pages": (num_slots,) int32,  # leading SHARED (cached) entries
      "page_ref":     (num_pages,) int32,  # active readers per shared page
      "free_stack":   (num_pages,) int32,  # stack[0:free_top] = free pages
      "free_top":     () int32,
    }

``alloc_pages`` tracks ownership, not occupancy: the scheduler allocates a
request's worst case (``ceil((prompt+max_new)/page_size)``) up front, so a
slot owns pages its length has not reached yet — free/defrag must treat
those as live (freeing by ``ceil(len/page_size)`` would leak the tail).
It bounds the slot's row RANGE, not a live-page count: a sliding-window
slot's leading entries may be NULLED mid-flight (``drop_slot_pages`` —
pages below the attention band return to the stack early) and
``release_slot`` skips null entries inside the range.

Prefix caching (``serving/prefix_cache.py``) adds page SHARING on top of
ownership: a slot's block-table row is ``[shared cached pages | owned
private pages | null...]``. The first ``shared_pages[slot]`` entries are
owned by the radix prefix cache and only READ by the slot (pages are
position-indexed, and the decode step never writes below a slot's length,
so read-only sharing is safe); ``page_ref`` counts, per page, how many
active slots currently share it — the eviction guard: a cached page may
return to the free stack only at refcount 0. Shared entries are installed
by ``alloc_slot_shared`` (refcount +1) and released by ``free_slot`` /
``release_slot`` (refcount -1, page NOT pushed to the free stack — the
cache still holds it).

Page 0 is the reserved NULL page: never allocated, and every dead block
table entry (idle slot, tail of a short sequence) points at it, so index
maps and masked writes always resolve to a valid page — static shapes,
no bounds branches. It is a SINK, not untouched storage: idle/done slots
write their fill tokens' K/V there and attend over it (outputs masked or
discarded) — no LIVE sequence ever reads it, but its contents are
arbitrary finite garbage, so never repurpose it as zeroed or poisonable
storage. The free list is a fixed-size int32 stack; alloc pops
``n`` pages off the top with a masked gather, free pushes them back with
a masked ``mode="drop"`` scatter — both jittable at one shape forever
(the ``n`` is a traced scalar, the mask is what varies).

The lane-alignment discipline mirrors ``ops/flat_buffer.py``: a page tile
is ``(page_size, head_dim)``, so ``page_size`` must be a sublane multiple
(8) and should be >= 16 for bf16 pools.

Tensor parallelism (``serving/tp.py``, docs/tp_serving.md): with
``init_paged_cache(..., mesh=)`` the pool is allocated GLOBALLY at the
full ``num_kv_heads`` and sharded along the head axis over the mesh's
``tp`` axis (:func:`cache_specs`) — each chip holds its
``num_kv_heads/tp`` head group of every page, while block tables / free
stack / lengths / refcounts stay replicated, so every pure-JAX pool op
in this module runs unchanged inside ``shard_map`` (none of them index
the head axis).

Quantized pools (``init_paged_cache(kv_dtype="int8"|"fp8")``,
docs/serving.md "Quantized KV pages"): pages store K/V narrow with one
symmetric f32 scale per ``(page, kv_head)`` beside the block table
(``k_scales``/``v_scales``, shape ``(num_pages, kv_local)``). The pool
ops here stay DTYPE-BLIND — they move page *names*, and a page's scale
rides with the page: alloc resets a fresh private page's scales to 0,
defrag gathers scales through the same permutation as the pages, and
shared (prefix-cached) pages keep their scales across sharers. Under TP
the scales shard along the same kv-head axis as the pages (dim 1).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from apex_tpu.amp.policy import resolve_compute_dtype
from apex_tpu.mesh import MODEL_AXIS
from apex_tpu.ops._dispatch import cdiv
from apex_tpu.ops.quant import kv_cast, kv_qmax, resolve_kv_dtype
from apex_tpu.transformer.utils import divide
from apex_tpu.utils import metrics


def page_size_of(cache) -> int:
    return cache["layers"][0]["k_pages"].shape[2]


def num_pages_of(cache) -> int:
    return cache["layers"][0]["k_pages"].shape[0]


def pages_for(length, page_size: int):
    """Pages needed for ``length`` tokens (traced or static)."""
    if isinstance(length, int):
        return cdiv(length, page_size)
    return (length + page_size - 1) // page_size


def cache_specs(config, axis_name: str = MODEL_AXIS, *, kv_dtype=None):
    """PartitionSpec pytree mirroring the paged-cache structure for a
    tensor-parallel mesh (``serving/tp.py``): the per-layer K/V pools
    shard along the kv-HEAD axis (dim 1 — each chip holds
    ``num_kv_heads/tp`` heads of EVERY page, so its pool shard is
    ``1/tp`` the bytes), while the block tables, free stack, lengths,
    and refcounts stay replicated (the host admission/retirement logic
    reads them and is chip-count-blind). The tree is both the
    ``shard_map`` in/out spec for every engine program and the
    ``NamedSharding`` layout of the global cache.

    ``kv_dtype``: non-None adds the quantized pool's per-layer
    ``k_scales``/``v_scales`` ``(num_pages, kv)`` entries, sharded along
    the same kv-head axis (dim 1) as the pages — per-chip scale bytes
    halve with the pool shard."""
    kv = PartitionSpec(None, axis_name)
    rep = PartitionSpec()
    layer = {"k_pages": kv, "v_pages": kv}
    if kv_dtype is not None:
        layer.update({"k_scales": kv, "v_scales": kv})
    return {
        "layers": [dict(layer) for _ in range(config.num_layers)],
        "block_tables": rep, "len": rep, "alloc_pages": rep,
        "shared_pages": rep, "page_ref": rep, "free_stack": rep,
        "free_top": rep,
    }


def init_paged_cache(config, num_slots: int, *, num_pages: int,
                     page_size: int = 16,
                     max_pages_per_seq: Optional[int] = None, dtype=None,
                     kv_dtype=None, mesh=None,
                     axis_name: str = MODEL_AXIS,
                     abstract: bool = False):
    """Allocate the shared page pool + empty slot state.

    ``num_pages`` includes the reserved null page 0, so the usable
    capacity is ``(num_pages - 1) * page_size`` tokens across all
    in-flight sequences. ``max_pages_per_seq`` bounds one sequence's block
    table (default: enough for ``max_position_embeddings``).

    ``mesh`` (a ``Mesh`` or ``AbstractMesh`` whose ``axis_name`` axis has
    size ``config.tensor_parallel_size``) allocates the GLOBAL
    tensor-parallel pool instead: the K/V pools hold ALL
    ``num_kv_heads`` and are sharded along the head axis per
    :func:`cache_specs` — each chip's shard is its local head group, so
    a pool that misses one chip's HBM fits the mesh — and everything
    else is replicated. ``abstract=True`` (implied by an
    ``AbstractMesh``) returns ``ShapeDtypeStruct`` leaves instead of
    materializing — the trace/AOT-compile form (a real ``Mesh`` stamps
    the NamedShardings on the structs; an ``AbstractMesh`` cannot).

    ``kv_dtype`` (``"int8"`` / ``"fp8"``, docs/serving.md "Quantized KV
    pages"): store the pages at the narrow dtype with per-``(page,
    kv_head)`` symmetric f32 scales (``k_scales``/``v_scales``) in each
    layer dict — roughly 2x the slots per pool byte at bf16 parity
    tolerance. Mutually exclusive with ``dtype`` (the page dtype IS the
    quantized dtype)."""
    if kv_dtype is not None and dtype is not None:
        raise ValueError("kv-dtype-conflict: pass dtype= OR kv_dtype=, "
                         "not both — a quantized pool's page dtype is "
                         "the quantized dtype")
    quant = resolve_kv_dtype(kv_dtype)
    if page_size % 8 != 0:
        raise ValueError(f"page_size must be a sublane multiple (8), got "
                         f"{page_size}")
    if num_pages < 2:
        raise ValueError("num_pages must be >= 2 (page 0 is reserved)")
    kv_heads = getattr(config, "num_kv_heads", config.num_heads)
    kv_local = divide(kv_heads, config.tensor_parallel_size)
    kv_dim = kv_local
    if mesh is not None:
        tp_world = dict(mesh.shape).get(axis_name)
        if tp_world is None:
            raise ValueError(f"mesh has no {axis_name!r} axis (axes: "
                             f"{tuple(dict(mesh.shape))})")
        if tp_world != config.tensor_parallel_size:
            raise ValueError(
                f"mesh {axis_name!r} axis size {tp_world} != "
                f"config.tensor_parallel_size="
                f"{config.tensor_parallel_size} — the model's shard "
                "shapes and the pool's head sharding would disagree")
        kv_dim = kv_local * tp_world            # the GLOBAL head count
    d = config.head_dim
    if quant is not None:
        dt = quant[0]
    else:
        dt = dtype if dtype is not None \
            else resolve_compute_dtype(config.dtype)
    if max_pages_per_seq is None:
        max_pages_per_seq = cdiv(config.max_position_embeddings, page_size)
    shape = (num_pages, kv_dim, page_size, d)
    scale_shape = (num_pages, kv_dim)
    if mesh is not None and (abstract or not isinstance(mesh, Mesh)):
        # trace/AOT form: no buffers, just (sharded) shapes
        specs = cache_specs(config, axis_name, kv_dtype=kv_dtype)
        stamp = isinstance(mesh, Mesh)

        def sds(sh, dt_, spec):
            sharding = NamedSharding(mesh, spec) if stamp else None
            return jax.ShapeDtypeStruct(sh, dt_, sharding=sharding)

        kv_spec = specs["layers"][0]["k_pages"]
        rep = PartitionSpec()

        def layer_sds():
            lc = {"k_pages": sds(shape, dt, kv_spec),
                  "v_pages": sds(shape, dt, kv_spec)}
            if quant is not None:
                sc_spec = specs["layers"][0]["k_scales"]
                lc["k_scales"] = sds(scale_shape, jnp.float32, sc_spec)
                lc["v_scales"] = sds(scale_shape, jnp.float32, sc_spec)
            return lc

        return {
            "layers": [layer_sds() for _ in range(config.num_layers)],
            "block_tables": sds((num_slots, max_pages_per_seq), jnp.int32,
                                rep),
            "len": sds((num_slots,), jnp.int32, rep),
            "alloc_pages": sds((num_slots,), jnp.int32, rep),
            "shared_pages": sds((num_slots,), jnp.int32, rep),
            "page_ref": sds((num_pages,), jnp.int32, rep),
            "free_stack": sds((num_pages,), jnp.int32, rep),
            "free_top": sds((), jnp.int32, rep),
        }
    def build():
        def layer_buf():
            lc = {"k_pages": jnp.zeros(shape, dt),
                  "v_pages": jnp.zeros(shape, dt)}
            if quant is not None:
                lc["k_scales"] = jnp.zeros(scale_shape, jnp.float32)
                lc["v_scales"] = jnp.zeros(scale_shape, jnp.float32)
            return lc
        layers = [layer_buf() for _ in range(config.num_layers)]
        return {
            "layers": layers,
            "block_tables": jnp.zeros((num_slots, max_pages_per_seq),
                                      jnp.int32),
            "len": jnp.zeros((num_slots,), jnp.int32),
            "alloc_pages": jnp.zeros((num_slots,), jnp.int32),
            "shared_pages": jnp.zeros((num_slots,), jnp.int32),
            "page_ref": jnp.zeros((num_pages,), jnp.int32),
            # pages 1..num_pages-1 free; popped from the top of the stack
            "free_stack": jnp.arange(1, num_pages + 1, dtype=jnp.int32
                                     ) % num_pages,
            "free_top": jnp.asarray(num_pages - 1, jnp.int32),
        }

    if mesh is None:
        return build()
    # allocate ALREADY sharded (jit with out_shardings): materializing
    # the global pool on one device first would OOM at exactly the
    # shapes TP exists for (a pool bigger than one chip's HBM)
    shardings = jax.tree.map(lambda spec: NamedSharding(mesh, spec),
                             cache_specs(config, axis_name,
                                         kv_dtype=kv_dtype),
                             is_leaf=lambda x: isinstance(
                                 x, PartitionSpec))
    return jax.jit(build, out_shardings=shardings)()


def free_page_count(cache):
    return cache["free_top"]


def observe_pool(cache, labels: Optional[dict] = None) -> dict:
    """Publish the pool's health gauges (docs/observability.md catalog):
    ``kv_pool.free_pages``, ``kv_pool.pages_total`` (usable, i.e. minus
    the null page), ``kv_pool.shared_pages_active`` (pages with
    ``page_ref > 0`` — currently shared by live readers), and
    ``kv_pool.page_refs_total`` (sum of active refcounts). ``labels``
    distinguishes pools (the engine passes its ``engine`` label — two
    engines' pools must not clobber one gauge). HOST-side only: reads
    two small device arrays (a scalar and the per-page refcounts) — the
    scheduler calls it at its sync boundaries, never from jitted code.
    Returns the gauge values as a dict."""
    refs = np.asarray(cache["page_ref"])
    vals = {
        "kv_pool.free_pages": int(np.asarray(cache["free_top"])),
        "kv_pool.pages_total": num_pages_of(cache) - 1,
        "kv_pool.shared_pages_active": int((refs > 0).sum()),
        "kv_pool.page_refs_total": int(refs.sum()),
    }
    for name, v in vals.items():
        metrics.gauge(name, labels=labels).set(v)
    return vals


def _reset_page_scales(cache, page_ids):
    """Zero the quantized-pool scales of freshly allocated PRIVATE pages
    (no-op on a full-precision pool). The requantize-on-grow append and
    the prefill scatter both trust scale 0 == "page holds nothing yet";
    a previous occupant's stale scale would silently inflate the new
    occupant's quantization grid. ``page_ids`` may contain 0 (the null
    page) for masked-out entries — page 0's scale is garbage like its
    contents and is never read by a live slot."""
    if "k_scales" not in cache["layers"][0]:
        return cache["layers"]
    zero = jnp.zeros(page_ids.shape + cache["layers"][0]["k_scales"]
                     .shape[1:], jnp.float32)
    return [dict(lc, k_scales=lc["k_scales"].at[page_ids].set(zero),
                 v_scales=lc["v_scales"].at[page_ids].set(zero))
            for lc in cache["layers"]]


def alloc_slot(cache, slot, n_pages):
    """Pop ``n_pages`` pages off the free stack and install them as slot
    ``slot``'s block table row (entries past ``n_pages`` point at the null
    page). ``slot``/``n_pages`` may be traced. The CALLER must ensure
    ``free_page_count(cache) >= n_pages`` (the scheduler's admission
    check) — the stack read clamps, so an over-alloc would silently hand
    out duplicate pages."""
    bt, stack, top = (cache["block_tables"], cache["free_stack"],
                      cache["free_top"])
    max_pages = bt.shape[1]
    num_pages = stack.shape[0]
    idx = jnp.arange(max_pages, dtype=jnp.int32)
    take = idx < n_pages
    src = jnp.clip(top - 1 - idx, 0, num_pages - 1)
    row = jnp.where(take, stack[src], 0)
    out = dict(cache)
    out["free_top"] = top - jnp.asarray(n_pages, jnp.int32)
    out["block_tables"] = bt.at[slot].set(row)
    out["alloc_pages"] = cache["alloc_pages"].at[slot].set(
        jnp.asarray(n_pages, jnp.int32))
    out["shared_pages"] = cache["shared_pages"].at[slot].set(0)
    out["layers"] = _reset_page_scales(cache, row)
    return out


def alloc_slot_shared(cache, slot, shared_row, n_shared, n_private):
    """Install slot ``slot``'s block table row as ``[shared cached pages |
    freshly popped private pages | null...]``: the first ``n_shared``
    entries come from ``shared_row`` (physical pages the prefix cache
    holds — refcount +1 each, NOT popped from the stack), the next
    ``n_private`` pop off the free stack as in ``alloc_slot``. Same caller
    contract: ``free_page_count(cache) >= n_private``."""
    bt, stack, top = (cache["block_tables"], cache["free_stack"],
                      cache["free_top"])
    max_pages = bt.shape[1]
    num_pages = stack.shape[0]
    n_shared = jnp.asarray(n_shared, jnp.int32)
    n_private = jnp.asarray(n_private, jnp.int32)
    idx = jnp.arange(max_pages, dtype=jnp.int32)
    take_priv = jnp.logical_and(idx >= n_shared, idx < n_shared + n_private)
    src = jnp.clip(top - 1 - (idx - n_shared), 0, num_pages - 1)
    row = jnp.where(idx < n_shared, shared_row,
                    jnp.where(take_priv, stack[src], 0))
    out = dict(cache)
    out["free_top"] = top - n_private
    out["block_tables"] = bt.at[slot].set(row)
    out["alloc_pages"] = cache["alloc_pages"].at[slot].set(n_private)
    out["shared_pages"] = cache["shared_pages"].at[slot].set(n_shared)
    ref_ids = jnp.where(idx < n_shared, shared_row, num_pages)  # OOB drops
    out["page_ref"] = cache["page_ref"].at[ref_ids].add(1, mode="drop")
    # only the freshly popped PRIVATE pages reset their scales — the
    # shared prefix pages keep theirs (shared pages are shared scales).
    # Gated so the fp pool's program never carries the dead page-id
    # select (the helper itself no-ops on fp pools, its argument not)
    if "k_scales" in cache["layers"][0]:
        out["layers"] = _reset_page_scales(
            cache, jnp.where(take_priv, row, 0))
    return out


def release_slot(cache, slot, keep):
    """Retire slot ``slot`` with page-level disposition: every table entry
    in the slot's ``shared + owned`` range with ``keep[j]`` False returns
    to the free stack; entries with ``keep[j]`` True leave the slot WITHOUT
    touching the stack (they are — or just became — prefix-cache property).
    The leading ``shared_pages[slot]`` entries additionally drop their
    ``page_ref`` by 1 (this slot stops reading them; whether they were
    kept or freed is the CALLER's eviction decision — the prefix cache
    only frees them at refcount 0). Resets the row/len/alloc/shared."""
    bt, stack, top = (cache["block_tables"], cache["free_stack"],
                      cache["free_top"])
    max_pages = bt.shape[1]
    num_pages = stack.shape[0]
    row = bt[slot]
    sh = cache["shared_pages"][slot]
    total = sh + cache["alloc_pages"][slot]
    idx = jnp.arange(max_pages, dtype=jnp.int32)
    # entries inside the owned range may already be NULL: a sliding-window
    # slot drops pages below its attention band mid-flight
    # (``drop_slot_pages``) — those freed already and must not push the
    # null page onto the stack here
    nonnull = row != 0
    freeable = jnp.logical_and(
        jnp.logical_and(idx < total, jnp.logical_not(keep)), nonnull)
    n_free = jnp.sum(freeable.astype(jnp.int32))
    pos = jnp.cumsum(freeable.astype(jnp.int32)) - 1
    dst = jnp.where(freeable, top + pos, num_pages)   # OOB -> dropped
    out = dict(cache)
    out["free_stack"] = stack.at[dst].set(row, mode="drop")
    out["free_top"] = top + n_free
    ref_ids = jnp.where(jnp.logical_and(idx < sh, nonnull), row, num_pages)
    out["page_ref"] = cache["page_ref"].at[ref_ids].add(-1, mode="drop")
    out["block_tables"] = bt.at[slot].set(jnp.zeros((max_pages,), jnp.int32))
    out["len"] = cache["len"].at[slot].set(0)
    out["alloc_pages"] = cache["alloc_pages"].at[slot].set(0)
    out["shared_pages"] = cache["shared_pages"].at[slot].set(0)
    return out


def free_slot(cache, slot):
    """Retire slot ``slot``: push ALL its owned pages (``alloc_pages``,
    not just the length-covered prefix) back onto the free stack, reset
    its block table row to the null page, and zero its length. Shared
    (prefix-cached) leading entries are NOT pushed — they stay cache
    property and only drop their refcount (``release_slot`` with
    ``keep = shared prefix``); without prefix caching ``shared_pages`` is
    0 and this frees exactly the owned set as before."""
    max_pages = cache["block_tables"].shape[1]
    keep = (jnp.arange(max_pages, dtype=jnp.int32)
            < cache["shared_pages"][slot])
    return release_slot(cache, slot, keep)


def drop_slot_pages(cache, slot, upto):
    """Free the pages behind slot ``slot``'s leading ``upto`` block-table
    entries and null the entries — the sliding-window page-eviction trick
    (docs/serving.md): once a page's positions all sit at or below the
    attention band's floor, no future decode step of this slot can read
    it (the band only moves forward), so the page is dead storage and
    returns to the free stack. Entries already dropped (null) are
    skipped, so repeated calls with a monotonically growing ``upto`` free
    each page exactly once; a windowed slot's steady-state footprint is
    O(window) pages regardless of generation length — the paged analog of
    the rolling ring buffer.

    CALLER contract: the dropped entries must be PRIVATE pages (the
    engine refuses ``prefix_cache`` for sliding-window models, so a
    windowed slot never holds shared entries) and fully below the band.
    ``alloc_pages`` is NOT decremented — it bounds the slot's row RANGE,
    and ``release_slot`` skips the nulled entries at retirement."""
    bt, stack, top = (cache["block_tables"], cache["free_stack"],
                      cache["free_top"])
    max_pages = bt.shape[1]
    num_pages = stack.shape[0]
    row = bt[slot]
    idx = jnp.arange(max_pages, dtype=jnp.int32)
    droppable = jnp.logical_and(idx < jnp.asarray(upto, jnp.int32),
                                row != 0)
    n = jnp.sum(droppable.astype(jnp.int32))
    pos = jnp.cumsum(droppable.astype(jnp.int32)) - 1
    dst = jnp.where(droppable, top + pos, num_pages)  # OOB -> dropped
    out = dict(cache)
    out["free_stack"] = stack.at[dst].set(row, mode="drop")
    out["free_top"] = top + n
    out["block_tables"] = bt.at[slot].set(jnp.where(droppable, 0, row))
    return out


#: pages moved per gather/promote program call (docs/serving.md "Tiered
#: KV pool"): the fixed tile-batch shape keeps both programs at ONE
#: compile each — a demote/promote of any depth is a loop of these
HOST_COPY_CHUNK = 8


def tile_specs(config, axis_name: str = MODEL_AXIS, *, kv_dtype=None):
    """PartitionSpec pytree for one gather/promote tile batch (the
    ``gather_pages`` result / ``promote_pages`` operand): per-layer
    ``(HOST_COPY_CHUNK, kv, page_size, d)`` K/V tiles shard along the
    kv-HEAD axis (dim 1) exactly like the pool pages they were cut from,
    so under TP each chip gathers/scatters its own head-shard and the
    host tier holds the pages at FULL head width (``serving/tp.py``
    maps the ``"tiles"`` compile role to this tree)."""
    kv = PartitionSpec(None, axis_name)
    layer = {"k_pages": kv, "v_pages": kv}
    if kv_dtype is not None:
        layer.update({"k_scales": kv, "v_scales": kv})
    return [dict(layer) for _ in range(config.num_layers)]


def gather_pages(cache, pages):
    """Read ``HOST_COPY_CHUNK`` pages' K/V tiles (and, quantized pools,
    their per-``(page, kv_head)`` scales) out of the pool — the demote
    half of the tiered pool (docs/serving.md "Tiered KV pool"): the
    frontend dispatches this BEFORE ``evict_pages`` returns the ids to
    the free stack, so program order on the device stream guarantees the
    copy reads the pages before any re-allocation overwrites them.
    ``pages`` is a fixed ``(HOST_COPY_CHUNK,)`` int32 row, null-padded —
    a null entry gathers page 0's garbage, which the caller discards.
    Pure read: the cache is NOT donated (it stays live)."""
    pages = jnp.asarray(pages, jnp.int32)
    return [{key: lc[key][pages] for key in lc} for lc in cache["layers"]]


def promote_pages(cache, pages, n, tiles):
    """Scatter ``n`` host-resident page tiles into freshly popped pages
    — the promote half of the tiered pool. ``pages`` holds the physical
    destinations (the caller host-reads the top ``n`` free-stack entries,
    exactly the pages this op's ``free_top -= n`` retires from the free
    set — the same pop discipline as ``alloc_slot``, with the ids read
    host-side so the tile write and the stack accounting cannot
    disagree); entries past ``n`` sink to the null page like every other
    masked pool write. The tiles are the raw pool-dtype bytes (and f32
    scales) ``gather_pages`` demoted, written back verbatim — promote is
    bit-stable by construction, never a requantization. The promoted
    pages carry ``page_ref == 0``: they become prefix-cache property
    (the radix tree grafts them via ``insert_promoted``), and sharers
    refcount them through ``alloc_slot_shared`` as usual."""
    pages = jnp.asarray(pages, jnp.int32)
    n = jnp.asarray(n, jnp.int32)
    idx = jnp.arange(pages.shape[0], dtype=jnp.int32)
    dst = jnp.where(idx < n, pages, 0)
    out = dict(cache)
    out["layers"] = [
        {key: lc[key].at[dst].set(tile[key].astype(lc[key].dtype))
         for key in lc}
        for lc, tile in zip(cache["layers"], tiles)]
    out["free_top"] = cache["free_top"] - n
    return out


def evict_pages(cache, pages_row, n):
    """Push the first ``n`` entries of ``pages_row`` back onto the free
    stack — the prefix cache evicting refcount-0 pages it owns. The CALLER
    (the cache's LRU walk) guarantees the pages are reachable from no
    block table and have ``page_ref == 0``; this is the stack push only."""
    stack, top = cache["free_stack"], cache["free_top"]
    num_pages = stack.shape[0]
    n = jnp.asarray(n, jnp.int32)
    idx = jnp.arange(pages_row.shape[0], dtype=jnp.int32)
    dst = jnp.where(idx < n, top + idx, num_pages)    # OOB -> dropped
    out = dict(cache)
    out["free_stack"] = stack.at[dst].set(pages_row, mode="drop")
    out["free_top"] = top + n
    return out


def defrag_map(cache, extra_live=None):
    """Compact live pages to the low end of the pool (stable order),
    rebuild the free stack from actual liveness, and return
    ``(cache, new_idx)`` where ``new_idx[old_page] = new_page`` — the
    remap a host-side prefix cache needs to follow its pages.

    With a block-table indirection fragmentation never costs correctness
    or speed — any free page is as good as another — but compaction keeps
    the live set prefix-dense (cheap pool-prefix checkpointing / shrink)
    and doubles as a leak collector: a page reachable from no slot's table
    returns to the free stack even if an earlier free miscounted. O(pool)
    gather per layer — an explicit maintenance op, not a per-step one.

    ``extra_live``: optional ``(num_pages,)`` bool mask of pages live for
    reasons no block table shows — the prefix cache's refcount-0 resident
    pages. Omitting it with a prefix cache attached would collect the
    cache's pages as leaks (and hand them out while the radix tree still
    names them)."""
    bt = cache["block_tables"]
    num_pages = num_pages_of(cache)
    max_pages = bt.shape[1]

    # liveness bound = SHARED + OWNED entries (a slot's
    # preallocated-but-unwritten tail is live: its future tokens land
    # there; its shared prefix is live: its reads land there)
    n_used = cache["shared_pages"] + cache["alloc_pages"]
    used_entries = (jnp.arange(max_pages, dtype=jnp.int32)[None, :]
                    < n_used[:, None])                       # (slots, mp)
    live = jnp.zeros((num_pages,), bool).at[
        jnp.where(used_entries, bt, 0)].set(True)
    if extra_live is not None:
        live = jnp.logical_or(live, extra_live)
    live = live.at[0].set(True)                  # null page stays page 0
    n_live = jnp.sum(live.astype(jnp.int32))
    new_idx = jnp.where(live, jnp.cumsum(live.astype(jnp.int32)) - 1,
                        n_live + jnp.cumsum((~live).astype(jnp.int32)) - 1
                        ).astype(jnp.int32)
    old_of_new = jnp.zeros((num_pages,), jnp.int32).at[new_idx].set(
        jnp.arange(num_pages, dtype=jnp.int32))

    out = dict(cache)
    # a page's scale moves with the page through the same permutation —
    # remapped quantized contents stay bit-identical to pre-defrag
    out["layers"] = [
        {key: lc[key][old_of_new] for key in lc}
        for lc in cache["layers"]]
    out["block_tables"] = jnp.where(used_entries, new_idx[bt], 0)
    out["page_ref"] = cache["page_ref"][old_of_new]
    idx = jnp.arange(num_pages, dtype=jnp.int32)
    out["free_stack"] = jnp.where(idx < num_pages - n_live, n_live + idx, 0)
    out["free_top"] = (num_pages - n_live).astype(jnp.int32)
    return out, new_idx


def defrag(cache, extra_live=None):
    """``defrag_map`` without the remap (callers with no host-side page
    names to rewrite)."""
    return defrag_map(cache, extra_live)[0]


def prefill_into_pages(cache, slot, contig_layers, s0, *, start=0):
    """Scatter a CONTIGUOUS prefill cache (the models' flash-prefill
    output: per-layer ``k``/``v`` of shape ``(1, kv, len_bucket, d)``)
    into slot ``slot``'s already-allocated pages, and set its length to
    ``s0`` (traced OK; positions past ``s0`` — prompt-bucket padding —
    scatter to the null page). Position ``p`` lands in table entry
    ``p // page_size`` at offset ``p % page_size``.

    ``start``: first position to write (default 0). A shared-prefix
    admission prefills only the uncached tail — positions below ``start``
    are the prefix-cache pages the slot merely reads, and MUST NOT be
    scattered (they are shared, and the partially-computed prefix slots of
    the contiguous buffer may hold gathered — not recomputed — values
    anyway); they mask to the null-page sink like bucket padding."""
    bt = cache["block_tables"]
    ps = page_size_of(cache)
    max_pages = bt.shape[1]
    len_bucket = contig_layers[0]["k"].shape[2]
    pos = jnp.arange(len_bucket, dtype=jnp.int32)
    valid = jnp.logical_and(pos >= start, pos < s0)
    row = bt[slot]
    phys = jnp.where(valid, row[jnp.clip(pos // ps, 0, max_pages - 1)], 0)
    off = pos % ps

    out = dict(cache)
    quantized = "k_scales" in cache["layers"][0]
    if quantized:
        # quantize-on-write (docs/serving.md "Quantized KV pages"): each
        # written table entry gets a fresh per-(page, kv_head) symmetric
        # scale from ITS tokens' amax — alloc reset these pages to scale
        # 0, so set (not max) is exact. Entries below ``start`` (shared
        # prefix pages) and bucket padding have no valid positions: their
        # writes sink to the null page and their scale row targets page 0
        # — shared pages keep their shared scales.
        qmax = kv_qmax(cache["layers"][0]["k_pages"].dtype)
        nb = cdiv(len_bucket, ps)
        pad = nb * ps - len_bucket
        valid_p = jnp.pad(valid, (0, pad))
        ent_any = valid_p.reshape(nb, ps).any(axis=1)          # (nb,)
        page_e = jnp.where(ent_any, row[:nb], 0)
        ent_of = jnp.clip(pos // ps, 0, nb - 1)

        def scatter_q(pages, scales, x):
            xf = x.astype(jnp.float32)           # (len_bucket, kv, d)
            ax = jnp.where(valid[:, None, None], jnp.abs(xf), 0.0)
            ax = jnp.pad(ax, ((0, pad), (0, 0), (0, 0)))
            amax = ax.reshape(nb, ps, *x.shape[1:]).max(axis=(1, 3))
            sc = amax / qmax                                   # (nb, kv)
            inv = jnp.where(sc > 0, 1.0 / jnp.maximum(sc, 1e-30), 0.0)
            q = kv_cast(xf * inv[ent_of][:, :, None], pages.dtype, qmax)
            return (pages.at[phys, :, off, :].set(q),
                    scales.at[page_e].set(
                        jnp.where(ent_any[:, None], sc, 0.0)))

    new_layers = []
    for lc, src in zip(cache["layers"], contig_layers):
        k = src["k"][0].transpose(1, 0, 2)       # (len_bucket, kv, d)
        v = src["v"][0].transpose(1, 0, 2)
        if quantized:
            kp, ks = scatter_q(lc["k_pages"], lc["k_scales"], k)
            vp, vs = scatter_q(lc["v_pages"], lc["v_scales"], v)
            new_layers.append({"k_pages": kp, "v_pages": vp,
                               "k_scales": ks, "v_scales": vs})
        else:
            new_layers.append({
                "k_pages": lc["k_pages"].at[phys, :, off, :].set(
                    k.astype(lc["k_pages"].dtype)),
                "v_pages": lc["v_pages"].at[phys, :, off, :].set(
                    v.astype(lc["v_pages"].dtype)),
            })
    out["layers"] = new_layers
    out["len"] = cache["len"].at[slot].set(jnp.asarray(s0, jnp.int32))
    return out


# --------------------------------------------------------------------------
# pool sizing (the capacity lever the quantized pool exists for)
# --------------------------------------------------------------------------

def page_bytes(config, page_size: int = 16, *, kv_dtype=None,
               dtype=None) -> int:
    """Pool bytes ONE page costs across all layers: the K and V page
    tiles at the pool dtype, plus — quantized pools — their two f32
    per-(page, kv_head) scale entries. The honest per-page denominator
    for capacity planning: at ``page_size=16, head_dim=64`` an int8 page
    costs ``(16*64 + 4) / (2*16*64) ≈ 0.502`` of a bf16 page, which is
    where the ~2x slot capacity comes from."""
    quant = resolve_kv_dtype(kv_dtype)
    if quant is not None:
        dt = quant[0]
    else:
        dt = dtype if dtype is not None \
            else resolve_compute_dtype(config.dtype)
    kv_heads = getattr(config, "num_kv_heads", config.num_heads)
    kv_local = divide(kv_heads, config.tensor_parallel_size)
    per_tensor = kv_local * page_size * config.head_dim * \
        jnp.dtype(dt).itemsize
    if quant is not None:
        per_tensor += kv_local * jnp.dtype(jnp.float32).itemsize
    return 2 * per_tensor * config.num_layers


def max_slots_for_pool_bytes(config, pool_bytes: int, *,
                             pages_per_slot: int, page_size: int = 16,
                             kv_dtype=None, dtype=None) -> int:
    """How many ``pages_per_slot``-page slots a ``pool_bytes`` budget
    admits (the null page 0 is carved out first). Holding ``pool_bytes``
    fixed, ``kv_dtype='int8'`` admits ~2x the slots of the bf16 pool —
    the acceptance pin in ``tests/test_quantized_kv.py`` and the
    slot-capacity telemetry in ``tpu_decode_bench.py``."""
    pb = page_bytes(config, page_size, kv_dtype=kv_dtype, dtype=dtype)
    num_pages = pool_bytes // pb
    return max(int(num_pages - 1) // pages_per_slot, 0)
