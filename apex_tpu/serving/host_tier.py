"""Host-RAM spill tier under the device page pool (docs/serving.md
"Tiered KV pool").

At long contexts the page pool, not the weights, caps ``num_slots``
(docs/tp_serving.md "Pool sizing"), and before this tier an evicted
radix page or a discarded preemption spill was simply recomputed — the
eviction-churn scenario lights ``prefix_cache.churn`` exactly there.
Copying a full page over the host link is strictly cheaper than
re-prefilling it (``cost.decode.host_tier.*`` prices both sides), so
refcount-0 pages the device pool can no longer afford DEMOTE here and
PROMOTE back into freshly allocated pages on the next prefix hit or
preemption resume, instead of being thrown away.

What this class is: pure host-side bookkeeping — a byte-budgeted LRU
over demoted page payloads, keyed by radix-node identity (the page's
full root->node token-key path, so a tier hit means exactly what a tree
hit means: these positions, these tokens). The payload is the page's
RAW pool-dtype bytes plus, on quantized pools, its per-``(page,
kv_head)`` f32 scales — an int8/fp8 page demotes and promotes
losslessly, and promote never requantizes (the PR 14 bit-stability
invariant: a full page's bytes are written once and never rewritten).

What this class is NOT: a device actor. Every device mutation stays in
``kv_pool`` ops the scheduler jits (``gather_pages`` on demote,
``promote_pages`` on promote); the engine's fixed-shape programs are
untouched and there is no copy-drain thread — demoted tiles arrive as
ASYNC device arrays (the gather is dispatched at a sync boundary,
before the eviction returns the pages to the free stack) and
``drain()`` converts them to host numpy inside the pump's
double-buffered host-work slot, while the next decode chunk runs.

Defrag composes for free: the tier names pages by TOKENS, not by
physical page id, so ``kv_pool.defrag_map`` has nothing here to remap —
promotion always pops fresh pages from the (possibly compacted) free
stack.

Instruments (docs/observability.md catalog): ``pool.host_tier_*`` —
resident bytes/pages gauges, demote/promote/lookup/hit/evicted
counters, and demote/promote copy-ms histograms.
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from apex_tpu.utils import metrics

__all__ = ["HostPageTier"]

#: a page's tier key: its radix path — the page-sized token-id runs from
#: the tree root down to (and including) the page's own run
PathKey = Tuple[Tuple[int, ...], ...]


class HostPageTier:
    """Byte-budgeted host-RAM LRU of demoted KV pages.

    Thread-safety: all tier state (the LRU map, the pending-demote list,
    the byte gauge) is guarded by ``self._lock``. The pump owns the
    call sites today, but the tier keeps the same single-lock discipline
    as the frontend's ingest side so the conc lint can pin its guard
    map (``tests/test_conc_lint.py``)."""

    def __init__(self, budget_bytes: int, *, page_size: int,
                 metrics_labels: Optional[dict] = None):
        if budget_bytes < 1:
            raise ValueError(
                f"host_tier budget_bytes must be >= 1, got {budget_bytes}")
        self.budget_bytes = int(budget_bytes)
        self.page_size = page_size
        self._lock = threading.Lock()
        # path-key -> (per-layer numpy payload dicts, payload bytes);
        # insertion order IS the LRU order (move_to_end on every hit)
        self._entries: "OrderedDict[PathKey, Tuple[List[dict], int]]" = \
            OrderedDict()
        self._resident_bytes = 0
        # demotes whose device->host copy is still in flight: each item
        # is (path keys, async device tile pytree, n live pages, t0)
        self._pending: List[tuple] = []
        labels = dict(metrics_labels) if metrics_labels else None
        self._g_bytes = metrics.gauge("pool.host_tier_resident_bytes",
                                      labels=labels)
        self._g_pages = metrics.gauge("pool.host_tier_resident_pages",
                                      labels=labels)
        self._c = {name: metrics.counter(f"pool.host_tier_{name}",
                                         labels=labels)
                   for name in ("demotes", "promotes", "lookups", "hits",
                                "evicted_pages")}
        self._c0 = {name: c.value for name, c in self._c.items()}
        self._h_demote = metrics.histogram("pool.host_tier_demote_copy_ms",
                                           labels=labels)
        self._h_promote = metrics.histogram("pool.host_tier_promote_copy_ms",
                                            labels=labels)

    # --- introspection ------------------------------------------------------

    def __len__(self) -> int:
        """Resident (drained) pages — pending demotes not yet counted."""
        with self._lock:
            return len(self._entries)

    @property
    def resident_bytes(self) -> int:
        with self._lock:
            return self._resident_bytes

    def stats(self) -> Dict[str, float]:
        """The tier's lifetime totals in ``stats()`` shape (the frontend
        merges these into the engine-stats dict as ``host_tier_*``)."""
        d = {name: c.value - self._c0[name] for name, c in self._c.items()}
        with self._lock:
            resident = self._resident_bytes
            pages = len(self._entries)
        return {
            "host_tier_resident_bytes": int(resident),
            "host_tier_resident_pages": int(pages),
            "host_tier_demotes": int(d["demotes"]),
            "host_tier_promotes": int(d["promotes"]),
            "host_tier_evicted_pages": int(d["evicted_pages"]),
            "host_tier_promote_hit_rate": (d["hits"]
                                           / max(d["lookups"], 1)),
        }

    def _observe_locked(self) -> None:
        self._g_bytes.set(self._resident_bytes)
        self._g_pages.set(len(self._entries))

    # --- demote (device -> host) --------------------------------------------

    def put_pending(self, keys: Sequence[PathKey], tiles, n: int) -> None:
        """Record one dispatched ``kv_pool.gather_pages`` batch: ``keys``
        name the first ``n`` tile rows (the rest is null-page padding).
        The device arrays stay ASYNC — nothing blocks here; ``drain()``
        converts them at the pump's host-work slot."""
        if n == 0:
            return
        with self._lock:
            self._pending.append((tuple(keys[:n]), tiles, n,
                                  time.perf_counter()))
        self._c["demotes"].inc(n)

    def drain(self) -> None:
        """Convert every pending demote's device tiles to host numpy
        (blocking only for copies not already complete — the histogram
        records the blocked span), split the batch into per-page LRU
        entries, and evict over-budget pages oldest-first."""
        with self._lock:
            pending, self._pending = self._pending, []
        for keys, tiles, n, _t0 in pending:
            t_enter = time.perf_counter()
            host = [{name: np.asarray(arr) for name, arr in lc.items()}
                    for lc in tiles]
            self._h_demote.observe((time.perf_counter() - t_enter) * 1e3)
            for i, key in enumerate(keys):
                payload = [{name: arr[i] for name, arr in lc.items()}
                           for lc in host]
                nbytes = sum(a.nbytes for lc in payload
                             for a in lc.values())
                if nbytes > self.budget_bytes:
                    continue             # one page over budget: drop it
                with self._lock:
                    old = self._entries.pop(key, None)
                    if old is not None:
                        self._resident_bytes -= old[1]
                    self._entries[key] = (payload, nbytes)
                    self._resident_bytes += nbytes
        self._evict_over_budget()

    def _evict_over_budget(self) -> None:
        evicted = 0
        with self._lock:
            while self._resident_bytes > self.budget_bytes and self._entries:
                _, (_, nbytes) = self._entries.popitem(last=False)
                self._resident_bytes -= nbytes
                evicted += 1
            self._observe_locked()
        if evicted:
            self._c["evicted_pages"].inc(evicted)

    # --- promote (host -> device) -------------------------------------------

    def run_length(self, base: PathKey, keys: Sequence[Tuple[int, ...]],
                   ) -> int:
        """How many consecutive pages past the tree-matched depth are
        resident: the longest r such that ``base + keys[:j+1]`` is held
        for every ``j < r``. One ``lookups`` tick per call (and a
        ``hits`` tick when r > 0): ``promote_hit_rate`` is hits over
        lookups. Bumps the run's LRU position."""
        r = 0
        path = tuple(base)
        with self._lock:
            for key in keys:
                path = path + (key,)
                if path not in self._entries:
                    break
                self._entries.move_to_end(path)
                r += 1
        self._c["lookups"].inc()
        if r:
            self._c["hits"].inc()
        return r

    def pop(self, path: PathKey) -> Optional[List[dict]]:
        """Take ownership of a resident page's payload (the promote
        path): removes the entry — the bytes are about to live in a
        device page the radix tree names, so keeping the host copy would
        double-count the budget. Returns None on a miss (the caller
        re-prefills instead)."""
        with self._lock:
            hit = self._entries.pop(path, None)
            if hit is None:
                return None
            self._resident_bytes -= hit[1]
            self._observe_locked()
        self._c["promotes"].inc()
        return hit[0]

    def observe_promote_ms(self, ms: float) -> None:
        """Record one promote batch's host->device copy span (the
        frontend times the dispatch-to-visible window at the sync
        boundary it already sits on)."""
        self._h_promote.observe(ms)
