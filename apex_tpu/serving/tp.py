"""Tensor-parallel paged serving: one logical engine over a ``tp`` mesh.

The single-chip :class:`~apex_tpu.serving.scheduler.PagedDecodeEngine`
owns three kinds of state: the paged KV pool (big, device), the model
variables (big, device), and the block-table/free-stack/slot metadata
(small, effectively host). Megatron-style tensor parallelism
(``apex_tpu/transformer/tensor_parallel``) already shards the model's
attention heads and MLP columns over the ``model`` axis — and GQA head
groups partition the SAME way, so the paged pool shards along its
kv-head axis with zero change to the paging logic:

- **K/V pool**: global ``(num_pages, num_kv_heads, page_size, d)``,
  sharded ``P(None, tp)`` — each chip holds ``num_kv_heads/tp`` heads of
  EVERY page, i.e. ``1/tp`` of the pool bytes. A model whose pool misses
  one chip's 16 GiB fits the mesh (the acceptance case in ``tpu_aot.py``
  compiles an 18 GiB-unsharded pool for ``v5e:2x4``).
- **Block tables / free stack / lengths / refcounts**: replicated. The
  host admission/retirement/preemption logic is chip-count-blind — the
  frontend, policy, prefix cache, and scenario stack compose untouched
  (they only see the engine interface).
- **Programs**: every engine program — admit, shared-prefix admit, the
  ``sync_every``-step decode scan, and the pool-maintenance ops — goes
  through the base engine's ``_compile`` seam, which this subclass
  wraps in ``shard_map`` over the mesh with per-role PartitionSpecs.
  Inside, the models' existing TP layers emit the Megatron collectives
  (QKV/MLP column-parallel → local heads, row-parallel all-reduce), the
  Pallas paged-attention kernel iterates its ``(kv_head, page)`` grid
  over the LOCAL head group, and greedy/sampled token selection gathers
  the vocab-parallel logits so every chip picks the identical token —
  no collective sampling step, and the replicated small state advances
  identically everywhere.

``tp=1`` reduces to the single-chip engine token-identically (psum /
all-gather over a size-1 axis are identity); TP=2 greedy decode is
pinned token-identical to the single-chip engine on the forced
8-CPU-device mesh in ``tests/test_tp_serving.py``.

Quantized-weight trees (``docs/serving.md`` "Quantized weight
streaming") shard through this module UNCHANGED: int8/fp8 leaves slice
along the same output/input channel axes as their fp counterparts, each
scale follows its weight's output-channel axis (replicated where the
weight is row-parallel), and int4's group-local nibble packing makes a
contiguous slice of whole groups along the packed axis exactly the
packed form of that shard — so ``infer_variable_specs`` /
``shard_model_variables`` need no quantization cases, and TP=2 over the
int8 tree is pinned token-identical to the single-chip int8 engine
(``tests/test_quantized_weights.py``).

Construction::

    cfg    = gpt2_small_config(tensor_parallel_size=2)
    model  = GPTModel(cfg)
    mesh   = tp_mesh(2)
    # shard a tp=1 checkpoint's full weights over the mesh
    v_tp, _ = shard_model_variables(model, v_full, mesh)
    engine = TensorParallelPagedEngine(model, v_tp, mesh=mesh,
                                       num_slots=..., page_size=16)
    outs, stats = engine.run(requests)      # or drive a ServingFrontend

An ``AbstractMesh`` (or ``abstract=True`` with a real/topology mesh)
builds a TRACE-ONLY engine — no buffers, ``ShapeDtypeStruct`` cache —
which is how the IR lint harness registers the TP programs devicelessly
and how ``tpu_aot.py`` AOT-compiles them for the v5e topology.
"""

from __future__ import annotations

import dataclasses
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from apex_tpu.mesh import MODEL_AXIS
from apex_tpu.serving import kv_pool
from apex_tpu.serving.scheduler import PagedDecodeEngine

__all__ = ["TensorParallelPagedEngine", "tp_mesh", "abstract_tp_mesh",
           "infer_variable_specs", "shard_model_variables"]

#: fused-projection params whose leading dim concatenates N logical
#: matrices (GPT's qkv, Llama's kv_proj / gate_up_proj). Megatron layout
#: gives each rank ITS heads' slice of EVERY chunk, so sharding a tp=1
#: checkpoint must interleave per-chunk blocks rank-major first — a
#: contiguous row split would hand rank 0 all of q and none of v.
FUSED_PARAM_CHUNKS = {"qkv": 3, "kv_proj": 2, "gate_up_proj": 2}


def tp_mesh(tp: int, devices=None, axis_name: str = MODEL_AXIS) -> Mesh:
    """A serving mesh: the first ``tp`` devices on one ``axis_name``
    axis (TP peers want adjacent devices — shortest ICI hops for the
    per-layer all-reduces, the same ordering argument as
    ``apex_tpu.mesh.build_mesh``)."""
    devices = list(devices) if devices is not None else jax.devices()
    if tp < 1:
        raise ValueError(f"tp must be >= 1, got {tp}")
    if len(devices) < tp:
        raise RuntimeError(
            f"tensor-parallel serving needs {tp} devices, have "
            f"{len(devices)} (on CPU: XLA_FLAGS="
            "--xla_force_host_platform_device_count=8)")
    return Mesh(np.asarray(devices[:tp]), (axis_name,))


def abstract_tp_mesh(tp: int, axis_name: str = MODEL_AXIS):
    """A deviceless ``AbstractMesh`` for trace-only TP engines (the IR
    lint harness / cost model trace the shard_map programs on any host,
    with any device count — no real mesh required)."""
    from jax.sharding import AbstractMesh

    try:
        return AbstractMesh(((axis_name, tp),))
    except TypeError:       # newer jax: AbstractMesh(shape, axis_names)
        return AbstractMesh((tp,), (axis_name,))


# --------------------------------------------------------------------------
# variable sharding
# --------------------------------------------------------------------------

def _path_name(path) -> str:
    return "/".join(str(getattr(k, "key", k)) for k in path)


def _abs_init(model):
    """Abstract variable tree of ``model`` (shapes only; the flax init
    clamp path is allowed outside shard_map, so TP configs eval_shape
    fine)."""
    return jax.eval_shape(lambda: model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32)))


def _split_axis(name: str, full, local, tp: int) -> int:
    """The one axis along which ``full`` (tp=1 shape) shards into
    ``local`` (per-rank shape): ``full[ax] == tp * local[ax]`` with
    every other dim equal."""
    candidates = [ax for ax in range(full.ndim)
                  if full.shape[ax] == tp * local.shape[ax]
                  and all(full.shape[i] == local.shape[i]
                          for i in range(full.ndim) if i != ax)]
    if len(candidates) != 1:
        raise ValueError(
            f"cannot infer the shard axis of {name!r}: tp=1 shape "
            f"{full.shape} vs tp={tp} shard {local.shape}")
    return candidates[0]


def infer_variable_specs(model, axis_name: str = MODEL_AXIS
                         ) -> Tuple[object, object]:
    """``(abs_full, specs)`` for a TP model's variables: the tp=1 twin's
    full (GLOBAL) shapes as a ``ShapeDtypeStruct`` tree, and the
    PartitionSpec per leaf — ``P(..., axis_name, ...)`` at the dim the
    TP layer shards (column/row/vocab split, inferred by which dim
    shrank between the tp=1 and tp=``n`` shard shapes), ``P()`` for
    replicated leaves (norms, biases, position table). The specs are
    both the ``shard_map`` in-spec for the ``variables`` argument of
    every engine program and the NamedSharding layout
    :func:`shard_model_variables` installs."""
    cfg = model.config
    tp = cfg.tensor_parallel_size
    abs_local = _abs_init(model)
    if tp == 1:
        return abs_local, jax.tree.map(lambda _: P(), abs_local)
    model1 = type(model)(dataclasses.replace(cfg, tensor_parallel_size=1))
    abs_full = _abs_init(model1)

    def spec_of(path, full, local):
        if full.shape == local.shape:
            return P()
        ax = _split_axis(_path_name(path), full, local, tp)
        return P(*(axis_name if i == ax else None
                   for i in range(full.ndim)))

    specs = jax.tree_util.tree_map_with_path(spec_of, abs_full, abs_local)
    return abs_full, specs


def _interleave_fused(leaf, ax: int, tp: int, chunks: int):
    """Reorder a fused ``chunks``-way projection so a contiguous 1/tp
    block along ``ax`` is one rank's Megatron shard (its slice of every
    chunk): ``[q | k | v]`` -> ``[q0 k0 v0 | q1 k1 v1 | ...]``.
    HOST-side numpy on purpose — see :func:`shard_model_variables`."""
    leaf = np.moveaxis(leaf, ax, 0)
    n = leaf.shape[0]
    per = n // (chunks * tp)
    rest = leaf.shape[1:]
    leaf = leaf.reshape(chunks, tp, per, *rest)
    leaf = np.swapaxes(leaf, 0, 1).reshape((n,) + tuple(rest))
    return np.moveaxis(leaf, 0, ax)


def shard_model_variables(model, variables, mesh,
                          axis_name: str = MODEL_AXIS):
    """Shard a tp=1 checkpoint's FULL variable tree over ``mesh`` for
    ``model`` (whose config carries ``tensor_parallel_size`` = the
    mesh's ``axis_name`` size). Returns ``(variables, specs)`` where
    every sharded leaf is a global array laid out so each rank's shard
    is exactly what the TP layers expect — fused projections
    (:data:`FUSED_PARAM_CHUNKS`) are interleaved per-chunk first — and
    replicated leaves live on every device. The sharded engine given
    these weights computes the SAME function as the tp=1 engine given
    ``variables`` (token-identical greedy decode,
    ``tests/test_tp_serving.py``)."""
    cfg = model.config
    tp = cfg.tensor_parallel_size
    abs_full, specs = infer_variable_specs(model, axis_name=axis_name)

    def put(path, leaf, ref, spec):
        # stage through HOST numpy: device_put from a host array lands
        # each chip's 1/tp slice directly, whereas a jnp view would
        # first materialize the FULL leaf on the default device — the
        # same OOM class init_paged_cache avoids for the pool
        leaf = np.asarray(leaf)
        if tuple(leaf.shape) != tuple(ref.shape):
            raise ValueError(
                f"variable {_path_name(path)!r} has shape {leaf.shape}; "
                f"expected the tp=1 FULL shape {ref.shape} (pass the "
                "unsharded checkpoint — this helper does the slicing)")
        sharded = any(s == axis_name for s in spec)
        if sharded:
            name = _path_name(path)
            chunks = next((c for key, c in FUSED_PARAM_CHUNKS.items()
                           if key in name), 1)
            if chunks > 1:
                ax = next(i for i, s in enumerate(spec) if s == axis_name)
                leaf = _interleave_fused(leaf, ax, tp, chunks)
        return jax.device_put(leaf, NamedSharding(mesh, spec))

    out = jax.tree_util.tree_map_with_path(put, variables, abs_full, specs)
    return out, specs


# --------------------------------------------------------------------------
# the engine
# --------------------------------------------------------------------------

class TensorParallelPagedEngine(PagedDecodeEngine):
    """One logical continuous-batching paged engine over a ``tp`` mesh.

    Drop-in for :class:`PagedDecodeEngine` — ``run()``, the
    ``ServingFrontend``, preemption, the prefix cache, sliding-window
    paging, and the scenario stack all compose unchanged (they drive the
    same compiled-program seams; the sharding lives entirely below
    them). ``model.config.tensor_parallel_size`` must equal the mesh's
    ``axis_name`` axis size, and ``variables`` must already be sharded
    (:func:`shard_model_variables`).

    ``abstract=True`` (implied by an ``AbstractMesh``) builds the
    trace-only form: no device buffers, ``ShapeDtypeStruct`` cache,
    ``variables=None`` — for the IR lint harness, the cost model, and
    the deviceless AOT tier. Such an engine cannot ``run()``.
    """

    def __init__(self, model, variables, *, mesh=None,
                 abstract: bool = False, **kwargs):
        cfg = model.config
        tp = cfg.tensor_parallel_size
        axis = kwargs.get("axis_name", MODEL_AXIS)
        self.mesh = mesh if mesh is not None else tp_mesh(tp,
                                                          axis_name=axis)
        mesh_tp = dict(self.mesh.shape).get(axis)
        if mesh_tp != tp:
            raise ValueError(
                f"config.tensor_parallel_size={tp} but the mesh's "
                f"{axis!r} axis has size {mesh_tp} — the model's shard "
                "shapes and the engine's head sharding would disagree")
        self.tp_world = tp
        self.abstract = bool(abstract) or not isinstance(self.mesh, Mesh)
        # quantized pools add per-(page, kv_head) scale arrays, which
        # shard P(None, axis) with the pages they scale — per-chip pool
        # bytes stay 1/tp of the (already ~2x smaller) global pool
        self._cache_specs = kv_pool.cache_specs(
            cfg, axis_name=axis, kv_dtype=kwargs.get("kv_dtype"))
        # tiered pool (docs/serving.md "Tiered KV pool"): gather/promote
        # tile batches shard along the kv-head axis with the pages they
        # were cut from — each chip demotes/promotes its own head-shard,
        # and the host tier holds every page at FULL head width
        self._tile_specs = kv_pool.tile_specs(
            cfg, axis_name=axis, kv_dtype=kwargs.get("kv_dtype"))
        _, self._var_specs = infer_variable_specs(model, axis_name=axis)
        # speculative decode: the draft pool and draft variables shard
        # over the SAME mesh (the draft model's own head/column layout),
        # so the s>1 verify and the draft loop run under one shard_map
        draft = kwargs.get("draft_model")
        self._draft_cache_specs = self._draft_var_specs = None
        if draft is not None:
            if draft.config.tensor_parallel_size != tp:
                raise ValueError(
                    f"draft model has tensor_parallel_size="
                    f"{draft.config.tensor_parallel_size}, target has "
                    f"{tp} — both must shard over the same mesh")
            self._draft_cache_specs = kv_pool.cache_specs(
                draft.config, axis_name=axis,
                kv_dtype=kwargs.get("kv_dtype"))
            _, self._draft_var_specs = infer_variable_specs(
                draft, axis_name=axis)
        super().__init__(model, variables, **kwargs)

    # --- the two seams the base engine exposes -----------------------------

    def _make_cache(self, num_slots, num_pages, page_size,
                    max_pages_per_seq, config=None):
        return kv_pool.init_paged_cache(
            config if config is not None else self.cfg, num_slots,
            num_pages=num_pages, page_size=page_size,
            max_pages_per_seq=max_pages_per_seq, mesh=self.mesh,
            axis_name=self.axis_name, abstract=self.abstract,
            kv_dtype=self.kv_dtype)

    def _compile(self, fn, in_roles, out_roles, donate=()):
        """shard_map ``fn`` over the mesh: the cache argument/result
        takes the head-sharded pool specs, the variables the inferred
        Megatron layout, everything else replicates. Outputs declared
        replicated really are — block-table/free-stack arithmetic is
        deterministic and runs on identical inputs everywhere, and
        token selection gathers the vocab-parallel logits before the
        argmax/categorical draw (``models/generation.py``) — so
        ``check_vma=False`` (the repo-wide setting; interpreted Pallas
        kernels cannot run under the vma checker) asserts nothing
        false."""
        spec_of = {"cache": self._cache_specs, "vars": self._var_specs,
                   "draft_cache": self._draft_cache_specs,
                   "draft_vars": self._draft_var_specs,
                   "tiles": self._tile_specs, "rep": P()}
        in_specs = tuple(spec_of[r] for r in in_roles)
        out_specs = tuple(spec_of[r] for r in out_roles)
        if len(out_specs) == 1:
            out_specs = out_specs[0]
        body = jax.shard_map(fn, mesh=self.mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=False)
        return jax.jit(body, donate_argnums=donate)
