"""Data-parallel replica serving: one router over N frontend+engine
replicas — load balancing, prefix-affinity routing, and failure
rebalancing.

This is the layer ROADMAP item 3 names (apex's ``apex.parallel`` DDP
stratum re-expressed for serving): the engine scales *up* with tensor
parallelism (``serving/tp.py``) and *out* with replication — N
:class:`~apex_tpu.serving.frontend.ServingFrontend` + engine replicas
(each optionally TP) behind one :class:`ReplicaRouter` that owns three
decisions:

- **Load balance** — replicas expose the queue-depth/active-slot
  signals ``/healthz`` already serves; the router sheds to the
  least-loaded replica when the preferred one is
  ``spill_queue_depth`` deeper than the best, and refuses with
  :class:`OverloadError` (``retry_after_s``) when EVERY live replica
  exceeds ``shed_queue_depth`` — overload is an explicit, retryable
  answer, never an unbounded queue.
- **Prefix affinity** — the request's system-prompt/tenant header (its
  leading ``affinity_tokens`` prompt tokens, or an explicit
  ``affinity_key=``) rendezvous-hashes to one replica, so one tenant's
  traffic lands where its radix cache already holds the header pages:
  the aggregate prefix hit-rate strictly beats round-robin on
  multi-tenant workloads (the scenario engine's A/B pins it).
  Rendezvous (highest-random-weight) hashing makes failure rebalancing
  minimal: a dead replica's keys spread over the survivors; every other
  key stays put.
- **Failure recovery** — a supervisor (the synchronous ``pump()``
  tick, or a background thread in ``start()`` mode) watches each
  replica's ``pump_alive``/``failure`` signals. A dead replica is
  marked unroutable and its in-flight requests re-submit to survivors
  with capped exponential backoff: the generated-so-far tokens fold
  into the resume prompt (the PR-6 preemption/resume idea, cross-
  replica — a survivor whose radix cache holds the prefix re-prefills
  only the tail; a cold cache pays a full re-prefill; greedy tokens are
  identical either way), and a request that exhausts ``retry_limit``
  failovers — or has no survivor left — fails terminally with
  :class:`~apex_tpu.serving.frontend.ServingError`. **No
  :class:`RouterHandle` ever hangs**: every submitted request either
  completes somewhere or raises.

The caller streams from a :class:`RouterHandle` (the same
:class:`~apex_tpu.serving.frontend.StreamHandle` surface) and never
learns which replica — or how many — served it; already-streamed tokens
are never re-delivered across a failover.

Graceful drain is first-class: :meth:`ReplicaRouter.drain_replica`
takes one replica out of rotation, lets its actives finish inside a
deadline, then *migrates* the stragglers (cancel-at-boundary + resume
elsewhere — the planned twin of failover); :meth:`ReplicaRouter.
shutdown` does the same for the whole router.

Fault injection (``serving/faults.py``) hooks the replicas' frontend
seams, so every failure mode here — kill, stall, reject, slow consumer
— is a seeded, replayable ``library.py`` chaos scenario
(docs/router.md, docs/scenarios.md).
"""

from __future__ import annotations

import dataclasses
import hashlib
import itertools
import threading
import time
from typing import Dict, List, Optional, Set, Tuple

import numpy as np

from apex_tpu.obs.events import EventLog
from apex_tpu.obs.fleet import (BurnRateAlerter, FleetCollector,
                                build_flight, mint_trace_id)
from apex_tpu.serving.frontend import ServingError, StreamHandle
from apex_tpu.serving.scheduler import Request
from apex_tpu.utils import metrics

__all__ = ["OverloadError", "ReplicaRouter", "RouterHandle",
           "RouterPolicy"]

#: per-process router ids, the ``router`` label on router instruments
_ROUTER_IDS = itertools.count()

#: router counters in the instrument registry (``router.<name>``)
_ROUTER_COUNTERS = ("routed", "failovers", "retries", "shed_requests",
                    "rejected_submits", "migrations", "replica_deaths")


class OverloadError(ServingError):
    """Every live replica is over the shed bound: the submission is
    refused, not queued. ``retry_after_s`` is the client's back-off
    hint (HTTP 429 semantics for the thread-level API)."""

    def __init__(self, message: str, retry_after_s: float = 0.0):
        super().__init__(message)
        self.retry_after_s = retry_after_s


@dataclasses.dataclass
class RouterPolicy:
    """The router's knobs, in one swappable object (the
    ``PriorityDeadlinePolicy`` pattern one layer up).

    ``routing`` — ``"affinity"`` (rendezvous-hash the prefix header,
    spill on load imbalance) or ``"round_robin"`` (ignore content; the
    A/B baseline). ``affinity_tokens`` bounds the hashed header.
    ``spill_queue_depth`` — spill off the affinity target once its
    queue is this much deeper than the least-loaded live replica's.
    ``shed_queue_depth`` — refuse (:class:`OverloadError`, with
    ``retry_after_s``) when every live replica's queue is at least
    this deep. ``retry_limit`` — failover/reject attempts per request
    before terminal failure. ``backoff_base_ms``/``backoff_cap_ms`` —
    capped exponential resubmission backoff (base·2^(attempt-1))."""

    routing: str = "affinity"
    affinity_tokens: int = 64
    spill_queue_depth: int = 8
    shed_queue_depth: int = 64
    retry_after_s: float = 0.5
    retry_limit: int = 3
    backoff_base_ms: float = 5.0
    backoff_cap_ms: float = 1000.0

    def __post_init__(self):
        if self.routing not in ("affinity", "round_robin"):
            raise ValueError(f"routing must be 'affinity' or "
                             f"'round_robin', got {self.routing!r}")
        if self.retry_limit < 0 or self.affinity_tokens < 1:
            raise ValueError("retry_limit >= 0 and affinity_tokens >= 1 "
                             "required")


class RouterHandle(StreamHandle):
    """The caller's stream across failovers: one queue, tokens in
    generation order with no re-delivery, ``result()``/iteration
    raising :class:`ServingError` when recovery is exhausted.
    ``failovers`` counts the replica deaths this request survived."""

    def __init__(self, request_id):
        super().__init__(request_id)
        self.failovers = 0


class _Replica:
    """One frontend+engine replica's routing state (all mutable fields
    guarded by the router's lock)."""

    __slots__ = ("index", "frontend", "alive", "draining", "started",
                 "routed", "failovers", "dead_reason")

    def __init__(self, index, frontend):
        self.index = index
        self.frontend = frontend
        self.alive = True
        self.draining = False
        self.started = False
        self.routed = 0
        self.failovers = 0               # requests failed over OFF it
        self.dead_reason: Optional[BaseException] = None


class _RouterEntry:
    """One live request's routing state (router-lock guarded)."""

    __slots__ = ("idx", "request", "handle", "affinity", "arrival",
                 "replica", "sub", "seg_sent", "delivered", "retries",
                 "not_before", "exclude", "migrate", "done")

    def __init__(self, idx, request, handle, affinity, arrival):
        self.idx = idx
        self.request = request
        self.handle = handle
        self.affinity = affinity
        self.arrival = arrival
        self.replica: Optional[int] = None
        self.sub: Optional[StreamHandle] = None
        self.seg_sent = 0                # current segment tokens forwarded
        self.delivered: List[int] = []   # tokens pushed to the handle
        self.retries = 0
        self.not_before = arrival
        self.exclude: Set[int] = set()   # replicas that just refused it
        self.migrate = False             # drain-migration in progress
        self.done = False


class _Record:
    """Per-request postmortem record, kept after completion (the
    lifecycle/report source; router-lock guarded)."""

    __slots__ = ("idx", "arrival_t", "first_t", "done_t",
                 "first_replica", "n_tokens", "failovers", "failed")

    def __init__(self, idx, arrival_t):
        self.idx = idx
        self.arrival_t = arrival_t
        self.first_t: Optional[float] = None
        self.done_t: Optional[float] = None
        self.first_replica: Optional[int] = None
        self.n_tokens = 0
        self.failovers = 0
        self.failed = False


def _rendezvous(key: str, replica: int) -> int:
    """Highest-random-weight score of (affinity key, replica) —
    process-independent (hashlib, not ``hash``)."""
    digest = hashlib.sha256(f"{key}|{replica}".encode()).digest()
    return int.from_bytes(digest[:8], "big")


class ReplicaRouter:
    """N serving replicas behind one submit surface; see the module
    docstring for the three decisions it owns.

    Drive it synchronously (``pump()`` per boundary / ``drain()`` —
    deterministic, what the scenario engine and chaos tests use) or
    start the whole stack (``start()``: every replica's background pump
    plus one supervisor thread ticking the router). All replicas must
    share model/tokenizer semantics — the router validates requests
    against replica 0's engine and treats the replica set as
    interchangeable."""

    def __init__(self, frontends, *, policy: Optional[RouterPolicy] = None,
                 clock=time.perf_counter):
        if not frontends:
            raise ValueError("need at least one replica frontend")
        self.policy = policy if policy is not None else RouterPolicy()
        self.clock = clock
        self.replicas = [_Replica(i, fe) for i, fe in enumerate(frontends)]
        self.eos_token_id = frontends[0].engine.eos_token_id
        self.events = EventLog(capacity=4096)
        self._lock = threading.Lock()
        self._entries: Dict[object, _RouterEntry] = {}
        self._queued: List[_RouterEntry] = []
        self._records: Dict[object, _Record] = {}
        self._accepting = True
        self._seq = itertools.count()
        self._rr_next = 0
        self._sup_thread: Optional[threading.Thread] = None
        self._sup_stop_evt = threading.Event()
        self._last_tick_t: Optional[float] = None
        self._flight_reason: Optional[str] = None
        self.last_flight: Optional[dict] = None
        self.alerter = BurnRateAlerter(events=self.events,
                                       clock=self.clock)
        self.fleet = FleetCollector(self, alerter=self.alerter,
                                    clock=self.clock)
        labels = {"router": str(next(_ROUTER_IDS))}
        self.obs_labels = labels
        self._C = {name: metrics.counter(f"router.{name}", labels=labels)
                   for name in _ROUTER_COUNTERS}
        self._c0 = {name: c.value for name, c in self._C.items()}
        self._alive_gauge = metrics.gauge("router.replicas_alive",
                                          labels=labels)
        self._depth_gauges = {
            rep.index: metrics.gauge(
                "router.replica_queue_depth",
                labels={**labels, "replica": str(rep.index)})
            for rep in self.replicas}
        self._alive_gauge.set(len(self.replicas))

    # --- ingest -------------------------------------------------------------

    def _affinity_key(self, request: Request) -> str:
        prompt = np.asarray(request.prompt, np.int32).reshape(-1)
        head = prompt[:self.policy.affinity_tokens]
        return hashlib.sha256(head.tobytes()).hexdigest()

    def submit(self, request: Request, *, request_id=None,
               affinity_key: Optional[str] = None) -> RouterHandle:
        """Route one request; returns its cross-replica streaming handle
        immediately. Thread-safe. Raises ``ValueError`` on a request no
        engine could serve, :class:`OverloadError` when every live
        replica is over the shed bound, and :class:`ServingError` when
        the router is draining or no replica is alive. ``affinity_key``
        overrides the hashed prompt header (e.g. a tenant id)."""
        self.replicas[0].frontend.engine._validate_request(request)
        if request.trace_id is None:
            # router-side mint: every replica (and every failover hop)
            # tags its spans with the SAME process-independent trace id
            request = dataclasses.replace(request,
                                          trace_id=mint_trace_id())
        now = self.clock()
        with self._lock:
            if not self._accepting:
                raise ServingError("router is draining")
            live = [rep for rep in self.replicas
                    if rep.alive and not rep.draining]
            if not live:
                raise ServingError("no live replicas")
            if all(rep.frontend.queue_depth >= self.policy.shed_queue_depth
                   for rep in live):
                self._C["shed_requests"].inc()
                self.events.emit("shed",
                                 queue_depths=[rep.frontend.queue_depth
                                               for rep in live])
                raise OverloadError(
                    f"all {len(live)} live replicas at or over the shed "
                    f"bound ({self.policy.shed_queue_depth} queued)",
                    retry_after_s=self.policy.retry_after_s)
            idx = request_id if request_id is not None else next(self._seq)
            if idx in self._records:
                raise ValueError(f"duplicate request_id {idx!r}")
            handle = RouterHandle(idx)
            key = affinity_key if affinity_key is not None \
                else self._affinity_key(request)
            entry = _RouterEntry(idx, request, handle, key, now)
            self._entries[idx] = entry
            self._records[idx] = _Record(idx, now)
        self._place(entry, now)
        return handle

    # --- routing ------------------------------------------------------------

    def _pick_locked(self, entry: _RouterEntry) -> Optional[_Replica]:
        live = [rep for rep in self.replicas
                if rep.alive and not rep.draining]
        if not live:
            return None
        candidates = [rep for rep in live
                      if rep.index not in entry.exclude]
        if not candidates:
            # everything has refused it once — retry anywhere rather
            # than starve (the retry_limit bounds the total attempts)
            entry.exclude.clear()
            candidates = live
        if self.policy.routing == "round_robin":
            rep = candidates[self._rr_next % len(candidates)]
            self._rr_next += 1
            return rep
        ranked = sorted(candidates,
                        key=lambda r: _rendezvous(entry.affinity, r.index),
                        reverse=True)
        preferred = ranked[0]
        depths = {rep.index: rep.frontend.queue_depth
                  for rep in candidates}
        least = min(candidates, key=lambda r: (depths[r.index], r.index))
        if (depths[preferred.index] - depths[least.index]
                > self.policy.spill_queue_depth):
            return least                 # load beats affinity
        return preferred

    def _resume_request(self, entry: _RouterEntry) -> Request:
        """The re-submission after a failover/migration: generated
        tokens fold into the prompt (the preemption-resume idea, cross-
        replica — a survivor with the prefix cached re-prefills only
        the tail, a cold one re-prefills everything; greedy tokens are
        identical either way), the budget shrinks by what was already
        delivered. The TTFT deadline is not re-armed — first token was
        already delivered or the miss already counted — but the TPOT
        SLO survives: it is a per-token target, and the resumed
        segment (the one a failover just slowed down) must keep
        counting against it."""
        base = self.request_prompt(entry)
        return Request(
            prompt=np.concatenate(
                [base, np.asarray(entry.delivered, np.int32)]),
            max_new_tokens=entry.request.max_new_tokens
            - len(entry.delivered),
            priority=entry.request.priority,
            arrival_time=entry.arrival,
            tpot_slo_ms=entry.request.tpot_slo_ms,
            trace_id=entry.request.trace_id)

    @staticmethod
    def request_prompt(entry) -> np.ndarray:
        return np.asarray(entry.request.prompt, np.int32).reshape(-1)

    def _place(self, entry: _RouterEntry, now: float) -> None:
        """Try to submit ``entry`` to a replica. The replica pick and
        all entry bookkeeping run under the router lock; the frontend
        ``submit`` call itself runs OUTSIDE it (it takes the replica's
        own ingest lock and does tracer/event work — holding the router
        lock across it would serialize routing behind replica ingest).
        On refusal the entry re-queues with backoff; with retries
        exhausted or no live replica it fails terminally."""
        with self._lock:
            if entry.done:
                return
            rep = self._pick_locked(entry)
            if rep is None:
                self._fail_entry_locked(
                    entry, ServingError(
                        f"request {entry.idx!r}: no live replicas to "
                        f"place it on"))
                return
            req = entry.request if not entry.delivered \
                else self._resume_request(entry)
        try:
            sub = rep.frontend.submit(req, request_id=entry.idx)
        except ServingError as exc:
            # refused (fault-injected reject, replica racing to death,
            # replica draining): exclude it this round and back off
            with self._lock:
                if entry.done:
                    return
                entry.exclude.add(rep.index)
                self._C["rejected_submits"].inc()
                self.events.emit("reject", request=entry.idx,
                                 replica=rep.index, error=repr(exc))
                entry.retries += 1
                if entry.retries > self.policy.retry_limit:
                    self._fail_entry_locked(entry, ServingError(
                        f"request {entry.idx!r} failed after "
                        f"{entry.retries} placement attempts"),
                        cause=exc)
                    return
                entry.not_before = now + self._backoff_s(entry.retries)
                self._queued.append(entry)
            return
        with self._lock:
            if entry.done:
                # terminally failed while we were inside the replica's
                # submit (supervision crash, shutdown leftovers): don't
                # install the sub — cancel it so the replica retires
                # the orphan at its next boundary instead of decoding
                # a request nobody will ever read
                sub.cancel()
                return
            entry.replica = rep.index
            entry.sub = sub
            entry.seg_sent = 0
            entry.exclude.clear()
            rep.routed += 1
            self._C["routed"].inc()
            self.events.emit("route", request=entry.idx,
                             replica=rep.index,
                             resumed_at=len(entry.delivered))

    def _backoff_s(self, attempt: int) -> float:
        p = self.policy
        return min(p.backoff_base_ms * 2.0 ** max(attempt - 1, 0),
                   p.backoff_cap_ms) * 1e-3

    # --- the supervisor tick ------------------------------------------------

    def pump(self) -> bool:
        """One synchronous router iteration: pump every live replica's
        frontend one boundary, then run the supervision tick (failure
        detection, token forwarding, failover resubmission, routing of
        backoff-expired requests). Returns True while work remains.
        Only for routers that were NOT ``start()``-ed — the background
        supervisor owns the tick there."""
        with self._lock:
            if self._sup_thread is not None:
                raise RuntimeError(
                    "router is running its background supervisor; "
                    "pump() is the synchronous driver")
            live = [rep.frontend for rep in self.replicas if rep.alive]
        for fe in live:
            try:
                fe.pump()
            except Exception:            # noqa: BLE001 — recorded as
                pass                     # fe.failure; the tick migrates
        self._tick()
        with self._lock:
            return bool(self._entries)

    # tpu-lint: host-boundary -- drives the replica pumps (host loop)
    def drain(self) -> None:
        """Pump until every submitted request has resolved (completed,
        migrated-and-completed, or terminally failed)."""
        while self.pump():
            pass

    def _tick(self) -> None:
        """The supervision pass — shared by ``pump()`` and the
        background supervisor thread. Any exception escaping it is
        TERMINAL for the router (the frontend pump's contract, one
        layer up): every outstanding handle fails with a
        :class:`ServingError` before the exception propagates, so a
        supervisor crash can never strand a consumer — the no-hung-
        handles guarantee survives bugs in the tick itself."""
        try:
            self._tick_impl()
        except Exception as exc:         # noqa: BLE001 — terminal
            err = ServingError(f"router supervision failed: {exc!r}")
            err.__cause__ = exc
            self.events.emit("supervisor_failed", error=repr(exc))
            with self._lock:
                for entry in list(self._entries.values()):
                    self._fail_entry_locked(entry, err)
                self._queued.clear()
            try:
                # the postmortem must never mask the original failure
                self.flight_snapshot(f"supervisor_failed:{exc!r}")
            except Exception:            # noqa: BLE001
                pass
            raise

    def fleet_targets(self) -> List[Tuple[str, bool, object]]:
        """The fleet collector's scrape list: ``(name, alive,
        frontend)`` per replica, snapshotted under the router lock so
        the scrape itself (pure I/O) runs with no lock held."""
        with self._lock:
            return [(f"replica{rep.index}", rep.alive, rep.frontend)
                    for rep in self.replicas]

    @property
    def last_tick_age_s(self) -> Optional[float]:
        """Seconds since the supervision tick last completed (None
        before the first tick) — the health doc's liveness signal for
        the router itself."""
        with self._lock:
            last = self._last_tick_t
        return None if last is None else max(self.clock() - last, 0.0)

    def _tick_impl(self) -> None:
        to_stop = []
        with self._lock:
            for rep in self.replicas:
                if rep.alive and rep.frontend.failure is not None:
                    self._mark_dead_locked(rep)
                    if rep.started:
                        to_stop.append(rep.frontend)
            entries = list(self._entries.values())
        for fe in to_stop:
            fe.stop()
        for entry in entries:
            with self._lock:
                delay = self._consume_delay_locked(entry)
            if delay:
                time.sleep(delay)        # the slow-consumer fault
            with self._lock:
                self._service_locked(entry, self.clock())
        self._route_due(self.clock())
        with self._lock:
            n_alive = sum(1 for rep in self.replicas if rep.alive)
            self._alive_gauge.set(n_alive)
            for rep in self.replicas:
                self._depth_gauges[rep.index].set(
                    rep.frontend.queue_depth if rep.alive else 0)
            self._last_tick_t = self.clock()
        # the fleet plane rides the tick, with NO router lock held: the
        # collector snapshots its targets under the lock and scrapes
        # between locks (docs/observability.md, "Fleet plane")
        self.fleet.tick()
        with self._lock:
            reason, self._flight_reason = self._flight_reason, None
        if reason is not None:
            self.flight_snapshot(reason)

    def _consume_delay_locked(self, entry: _RouterEntry) -> float:
        if entry.done or entry.replica is None:
            return 0.0
        hook = self.replicas[entry.replica].frontend.fault_hook
        if hook is None:
            return 0.0
        return hook.consume_delay_s(entry.idx)

    def _mark_dead_locked(self, rep: _Replica) -> None:
        rep.alive = False
        rep.dead_reason = rep.frontend.failure
        self._C["replica_deaths"].inc()
        self.events.emit("replica_dead", replica=rep.index,
                         error=repr(rep.dead_reason))
        if self._flight_reason is None:
            # the flight recorder fires at the END of this tick (the
            # snapshot takes the collector lock and scrapes — neither
            # belongs under the router lock)
            self._flight_reason = f"replica_dead:{rep.index}"

    def _forward_locked(self, entry: _RouterEntry, sub, now: float) -> None:
        toks = sub.tokens_so_far()
        new = toks[entry.seg_sent:]
        if new:
            rec = self._records[entry.idx]
            if rec.first_t is None:
                rec.first_t = now
                rec.first_replica = entry.replica
            for t in new:
                entry.delivered.append(t)
                entry.handle._push(t)
            rec.n_tokens = len(entry.delivered)
            entry.seg_sent = len(toks)

    def _service_locked(self, entry: _RouterEntry, now: float) -> None:
        """Forward new tokens, detect terminal sub states, fail over."""
        if entry.done:
            return
        sub = entry.sub
        if sub is None:
            return                       # queued — _route_due's business
        if entry.handle.cancelled and not sub.cancelled:
            sub.cancel()
        self._forward_locked(entry, sub, now)
        if not sub.done:
            return
        # re-read AFTER observing done: with background replica pumps,
        # tokens pushed between the snapshot above and the replica's
        # _finish/_fail would otherwise be dropped from the delivered
        # record right as we finalize (the handle orders every push
        # before its done flag, so this second read is complete)
        self._forward_locked(entry, sub, now)
        if sub.error is not None:        # the replica died under it
            self._failover_locked(entry, sub.error, now)
            return
        if entry.migrate and not entry.handle.cancelled \
                and not self._complete(entry):
            # drain migration: the replica cancelled it at a boundary;
            # resume the remainder elsewhere (tokens preserved)
            entry.migrate = False
            entry.sub = None
            entry.replica = None
            self._C["migrations"].inc()
            self.events.emit("migrate", request=entry.idx,
                             delivered=len(entry.delivered))
            entry.not_before = now
            self._queued.append(entry)
            return
        self._finish_locked(entry)

    def _complete(self, entry: _RouterEntry) -> bool:
        if len(entry.delivered) >= entry.request.max_new_tokens:
            return True
        eos = self.eos_token_id
        return (eos is not None and entry.delivered
                and entry.delivered[-1] == eos)

    def _finish_locked(self, entry: _RouterEntry) -> None:
        entry.done = True
        self._entries.pop(entry.idx, None)
        rec = self._records[entry.idx]
        rec.done_t = self.clock()
        rec.n_tokens = len(entry.delivered)
        rec.failovers = entry.handle.failovers
        entry.handle._finish(np.asarray(entry.delivered, np.int32))

    def _fail_entry_locked(self, entry: _RouterEntry,
                           exc: ServingError, *, cause=None) -> None:
        if cause is not None:
            exc.__cause__ = cause
        entry.done = True
        self._entries.pop(entry.idx, None)
        rec = self._records[entry.idx]
        rec.done_t = self.clock()
        rec.failovers = entry.handle.failovers
        rec.failed = True
        self.events.emit("request_failed", request=entry.idx,
                         error=str(exc))
        entry.handle._fail(exc)

    def _failover_locked(self, entry: _RouterEntry,
                         error: BaseException, now: float) -> None:
        """The dead replica's handle failed terminally; re-home the
        request on a survivor with capped exponential backoff, or fail
        it after ``retry_limit`` attempts."""
        dead = entry.replica
        entry.sub = None
        entry.replica = None
        entry.handle.failovers += 1
        entry.retries += 1
        if dead is not None:
            self.replicas[dead].failovers += 1
        self._C["failovers"].inc()
        self._C["retries"].inc()
        self.events.emit("failover", request=entry.idx, replica=dead,
                         delivered=len(entry.delivered),
                         attempt=entry.retries)
        if entry.handle.cancelled or self._complete(entry):
            # nothing left to recover — the stream already has its
            # tokens (cancel truncates; a complete request just ends)
            self._finish_locked(entry)
            return
        if entry.retries > self.policy.retry_limit:
            self._fail_entry_locked(entry, ServingError(
                f"request {entry.idx!r} failed after {entry.retries} "
                f"failover attempts"), cause=error)
            return
        entry.not_before = now + self._backoff_s(entry.retries)
        self._queued.append(entry)

    def _route_due(self, now: float) -> None:
        """Place queued entries whose backoff expired (each placement
        re-queues itself on failure); cancelled waiters finish with
        their delivered tokens."""
        due: List[_RouterEntry] = []
        with self._lock:
            still: List[_RouterEntry] = []
            for entry in self._queued:
                if entry.done:
                    continue
                if entry.handle.cancelled:
                    self._finish_locked(entry)
                    continue
                if now < entry.not_before:
                    still.append(entry)
                    continue
                due.append(entry)
            self._queued[:] = still
        for entry in due:
            self._place(entry, now)

    # --- background mode ----------------------------------------------------

    def start(self, supervise_interval_s: float = 0.002) -> None:
        """Start every replica's background pump and the router's
        supervisor thread (failure watch + forwarding at
        ``supervise_interval_s``)."""
        with self._lock:
            if self._sup_thread is not None:
                raise RuntimeError("router already started")
            reps = list(self.replicas)
        for rep in reps:
            rep.frontend.start()
        with self._lock:
            for rep in reps:
                rep.started = True
        self._sup_stop_evt.clear()

        def supervise():
            while not self._sup_stop_evt.is_set():
                self._tick()
                self._sup_stop_evt.wait(supervise_interval_s)

        thread = threading.Thread(target=supervise, daemon=True,
                                  name="serving-router-supervisor")
        with self._lock:
            self._sup_thread = thread
        thread.start()

    def stop(self, timeout: Optional[float] = 30.0) -> None:
        """Stop the supervisor and every replica pump (in-flight work is
        left as-is; use :meth:`shutdown` for a clean end-of-life)."""
        with self._lock:
            thread, self._sup_thread = self._sup_thread, None
            reps = [rep for rep in self.replicas if rep.started]
            for rep in reps:
                rep.started = False
        self._sup_stop_evt.set()
        if thread is not None:
            thread.join(timeout)
        for rep in reps:
            rep.frontend.stop()

    # --- graceful drain -----------------------------------------------------

    def _cancel_all_locked(self) -> None:
        for entry in self._entries.values():
            entry.handle.cancel()

    def shutdown(self, deadline_s: float = 30.0, *,
                 mode: str = "drain") -> None:
        """Router-wide graceful drain: stop accepting, resolve every
        in-flight request (finishing under ``mode="drain"``, cancelling
        under ``mode="cancel"`` or once the deadline expires), then
        stop the supervisor and shut every replica frontend down. Every
        handle reaches ``done``; unresolvable stragglers fail with
        :class:`ServingError`."""
        if mode not in ("drain", "cancel"):
            raise ValueError(f"shutdown mode must be 'drain' or "
                             f"'cancel', got {mode!r}")
        with self._lock:
            self._accepting = False
            threaded = self._sup_thread is not None
            if mode == "cancel":
                self._cancel_all_locked()
        deadline = self.clock() + deadline_s
        cancelled = mode == "cancel"
        budget: Optional[int] = None
        while True:
            with self._lock:
                work = bool(self._entries)
            if not work:
                break
            if not cancelled and self.clock() >= deadline:
                with self._lock:
                    self._cancel_all_locked()
                cancelled = True
                deadline = self.clock() + max(deadline_s, 2.0)
            if cancelled:
                if budget is None:
                    budget = 64 * len(self.replicas) + 64
                budget -= 1
                if budget < 0 or self.clock() >= deadline:
                    break
            if threaded:
                time.sleep(0.002)
            else:
                self.pump()
        self.stop()
        with self._lock:
            leftovers = list(self._entries.values())
            for entry in leftovers:
                self._fail_entry_locked(entry, ServingError(
                    f"router shutdown ({mode}) deadline expired"))
            self._queued.clear()
        for rep in self.replicas:
            # replicas get their own clean end-of-life (releases any
            # straggler pages; a failed replica skips straight through)
            rep.frontend.shutdown(deadline_s=2.0, mode="cancel")

    def drain_replica(self, index: int, deadline_s: float = 10.0, *,
                      migrate: bool = False) -> None:
        """Take one replica out of rotation: no new routes land on it,
        its active requests finish inside ``deadline_s`` — or are
        MIGRATED (cancelled at a sync boundary and resumed on a
        survivor, tokens preserved) once the deadline passes, or
        immediately with ``migrate=True``. The replica ends not-alive
        (out of the live set) with its pump stopped."""
        with self._lock:
            rep = self.replicas[index]
            if not rep.alive:
                return
            rep.draining = True
            self.events.emit("replica_drain", replica=index)
            threaded = self._sup_thread is not None
        deadline = self.clock() if migrate else self.clock() + deadline_s
        migrated = False
        budget: Optional[int] = None
        while True:
            with self._lock:
                mine = [e for e in self._entries.values()
                        if e.replica == index]
                if not mine:
                    break
                if not migrated and self.clock() >= deadline:
                    for entry in mine:
                        if entry.sub is not None:
                            entry.migrate = True
                            entry.sub.cancel()
                    migrated = True
            if migrated:
                if budget is None:
                    budget = 64 * len(self.replicas) + 64
                budget -= 1
                if budget < 0:
                    break
            if threaded:
                time.sleep(0.002)
            else:
                self.pump()
        stop_it = False
        with self._lock:
            rep.draining = False
            rep.alive = False
            stop_it = rep.started
            rep.started = False
            self.events.emit("replica_drained", replica=index)
        if stop_it:
            rep.frontend.stop()

    # --- the flight recorder ------------------------------------------------

    def flight_snapshot(self, reason: str, *,
                        tag: Optional[str] = None) -> dict:
        """Dump the correlated postmortem bundle (the flight recorder):
        the routing table and counters under the lock, a forced fleet
        scrape, every replica tracer's spans stitched by trace id, the
        replicas' event-ring tails, and the registry snapshot —
        schema-pinned (:data:`~apex_tpu.obs.fleet.FLIGHT_SCHEMA`),
        validated by :func:`~apex_tpu.obs.fleet.validate_flight`.
        Fires automatically on replica death and supervisor failure;
        call it directly for an on-demand snapshot. The latest bundle
        is kept on ``self.last_flight``."""
        with self._lock:
            routing = [{
                "replica": f"replica{rep.index}",
                "alive": rep.alive,
                "draining": rep.draining,
                "routed": rep.routed,
                "failovers": rep.failovers,
                "dead_reason": repr(rep.dead_reason)
                if rep.dead_reason is not None else None,
                "queue_depth": rep.frontend.queue_depth
                if rep.alive else 0,
            } for rep in self.replicas]
            counters = {name: int(c.value - self._c0[name])
                        for name, c in self._C.items()}
            router_events = self.events.tail(256)
        # scrape + stitch with NO router lock held (the collector takes
        # the lock itself via fleet_targets; tracer reads are the
        # tracers' own locks)
        self.fleet.tick(force=True)
        dumps: Dict[str, list] = {}
        replica_events: Dict[str, list] = {}
        for rep in self.replicas:        # the replica list never mutates
            name = f"replica{rep.index}"
            dumps[name] = rep.frontend.tracer.to_dicts()
            ring = getattr(getattr(rep.frontend, "engine", None),
                           "events", None)
            if ring is not None:
                replica_events[name] = ring.tail(256)
        doc = build_flight(reason=reason, routing=routing,
                           counters=counters,
                           router_events=router_events, dumps=dumps,
                           collector=self.fleet,
                           replica_events=replica_events or None,
                           tag=tag)
        with self._lock:
            self.last_flight = doc
        self.events.emit("flight_recorded", reason=reason,
                         replicas=len(dumps))
        return doc

    # --- report adapters (the scenario engine's tracer surface) -------------

    def lifecycle(self, request_id) -> Dict[str, object]:
        """Cross-replica lifecycle summary (the report builder's
        contract): TTFT/TPOT from the router's own forwarding
        timestamps — correct across failovers, where no single
        replica's tracer sees the whole request — plus queue-wait from
        the first serving replica's tracer when it survives."""
        with self._lock:
            rec = self._records.get(request_id)
            if rec is None:
                return {"request_id": request_id}
            arrival, first_t = rec.arrival_t, rec.first_t
            done_t, n = rec.done_t, rec.n_tokens
            first_replica = rec.first_replica
        out: Dict[str, object] = {"request_id": request_id}
        if first_t is not None:
            out["ttft_ms"] = (first_t - arrival) * 1e3
        if done_t is not None and first_t is not None and n > 1:
            out["tpot_ms"] = (done_t - first_t) * 1e3 / (n - 1)
        if n:
            out["new_tokens"] = n
        if first_replica is not None:
            sub_life = self.replicas[first_replica].frontend.tracer \
                .lifecycle(request_id)
            if "queue_wait_ms" in sub_life:
                out["queue_wait_ms"] = sub_life["queue_wait_ms"]
        return out

    def spans(self, request_id) -> list:
        """Every replica tracer's spans for ``request_id``, in replica
        order (deadline-miss instants survive the replica)."""
        out = []
        for rep in self.replicas:
            out.extend(rep.frontend.tracer.spans(request_id))
        return out

    # --- stats --------------------------------------------------------------

    def stats(self) -> dict:
        """Router-lifetime stats: routing/failover counters, recovery
        rate, and the replica-aggregated engine counters the scenario
        report embeds. ``failover_recovered_rate`` is the fraction of
        failover-surviving requests that completed (1.0 when nothing
        ever failed over — vacuous recovery is still recovery)."""
        with self._lock:
            d = {name: c.value - self._c0[name]
                 for name, c in self._C.items()}
            reps = [(rep.index, rep.alive, rep.routed, rep.frontend)
                    for rep in self.replicas]
            recs = list(self._records.values())
        per_replica = []
        agg: Dict[str, float] = {}
        for index, alive, routed, fe in reps:
            fd = fe.counter_deltas()
            per_replica.append({
                "replica": index, "alive": alive, "routed": routed,
                "admitted": int(fd["admitted"]),
                "retired": int(fd["retired"]),
                "prefix_hits": int(fd["prefix_hits"]),
                "preemptions": int(fd["preemptions"]),
                "queue_depth": fe.queue_depth if alive else 0,
            })
            for name, val in fd.items():
                agg[name] = agg.get(name, 0.0) + val
        failover_reqs = [r for r in recs if r.failovers > 0]
        recovered = [r for r in failover_reqs
                     if r.done_t is not None and not r.failed]
        stats = {
            "replicas": len(reps),
            "replicas_alive": sum(1 for _, alive, _, _ in reps if alive),
            "requests": len(recs),
            "completed": sum(1 for r in recs
                             if r.done_t is not None and not r.failed),
            "failed": sum(1 for r in recs if r.failed),
            "routed": int(d["routed"]),
            "failovers": int(d["failovers"]),
            "retries": int(d["retries"]),
            "shed_requests": int(d["shed_requests"]),
            "rejected_submits": int(d["rejected_submits"]),
            "migrations": int(d["migrations"]),
            "replica_deaths": int(d["replica_deaths"]),
            "failover_requests": len(failover_reqs),
            "failover_recovered": len(recovered),
            "failover_recovered_rate":
                len(recovered) / len(failover_reqs)
                if failover_reqs else 1.0,
            # replica-aggregated engine counters (the report's fields)
            "admitted": int(agg.get("admitted", 0)),
            "retired": int(agg.get("retired", 0)),
            "preemptions": int(agg.get("preemptions", 0)),
            "resumes": int(agg.get("resumes", 0)),
            "deadline_misses": int(agg.get("deadline_misses", 0)),
            "tpot_slo_misses": int(agg.get("tpot_slo_misses", 0)),
            "evicted_pages": int(agg.get("evicted_pages", 0)),
            "window_dropped_pages": int(agg.get("window_dropped_pages",
                                                0)),
            "prefix_hits": int(agg.get("prefix_hits", 0)),
            "prefix_hit_rate": (agg.get("prefix_hits", 0)
                                / max(agg.get("admitted", 0), 1)),
            "prefill_tokens_total": int(agg.get("prefill_tokens_total",
                                                0)),
            "prefill_tokens_computed":
                int(agg.get("prefill_tokens_computed", 0)),
            "prefill_tokens_skipped":
                int(agg.get("prefill_tokens_total", 0)
                    - agg.get("prefill_tokens_computed", 0)),
            "per_replica": per_replica,
        }
        for name, val in stats.items():
            if isinstance(val, (int, float)) and not isinstance(val, bool):
                metrics.record(f"router.{name}", val)
        # the federated fleet block (pinned shape — report.FLEET_FIELDS);
        # a dict, so the record loop above never sees it
        stats["fleet"] = self.fleet.block()
        return stats
