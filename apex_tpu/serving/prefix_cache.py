"""Shared-prefix KV cache: a ref-counted radix tree over the page pool.

The dominant serving pattern is thousands of requests sharing a system
prompt or few-shot header; without cross-request reuse every one of them
re-prefills the shared tokens. This module is the host-side half of
RadixAttention-style prefix caching (SGLang, Zheng et al. 2023) over the
existing paged pool (``kv_pool.py``): a radix tree keyed by PAGE-sized
runs of token ids whose nodes name physical pages already holding that
run's K/V at those absolute positions.

Granularity is one page (``page_size`` tokens), matching the pool's unit
of allocation and the copy-on-write boundary: a cached page is complete
and immutable, so matching, sharing, and eviction are all whole-page
moves. The partially-filled boundary page of a sequence is therefore
never shared — a new request recomputes (copies) it into a private page.

Lifecycle, as driven by ``scheduler.PagedDecodeEngine``:

- **Match** (admission): walk the tree down the prompt's full pages —
  capped at ``(s0 - 1) // page_size`` so at least one prompt token is
  always prefilled (the last-token logits seed sampling). Matched nodes
  are ``acquire``d (refcount +1, mirrored into the device-side
  ``page_ref``) and the slot's block table points straight at their
  pages; only the uncached tail is prefilled.
- **Insert** (retirement): the request's full-page prefix — prompt AND
  written generated tokens — moves into the tree instead of the free
  stack (``release_and_insert`` returns the per-entry keep mask for
  ``kv_pool.release_slot``). A page whose key a concurrent twin already
  inserted is a duplicate and frees normally.
- **Evict** (on demand): when admission finds the free stack short, LRU
  refcount-0 LEAVES leave the tree and return to the stack
  (``kv_pool.evict_pages``). Interior nodes are never evicted before
  their children (a child's positions extend the parent's — evicting the
  parent would orphan reachable state), and a refcount > 0 node is
  pinned by its active readers.

Correctness of sharing rests on two pool invariants: pages are
position-indexed (a cached page is only ever matched at the positions it
was written for — matches start at position 0 and extend page by page),
and the decode step never writes below a slot's length (a sharer's
writes land in its private tail pages, so cached pages are read-only).

The authoritative refcounts live in the device cache state
(``cache["page_ref"]``, int32 per page) so pool invariants are checkable
on-device; nodes mirror them host-side so admission and eviction never
force a device sync.
"""

from __future__ import annotations

import heapq
from collections import deque
from typing import List, Optional, Sequence

import numpy as np

from apex_tpu.utils import metrics

__all__ = ["PrefixCache"]

#: rolling retirement window for the ``prefix_cache.churn`` gauge
#: (evictions per retirement, averaged over the last N retirements)
CHURN_WINDOW = 64

#: bound on the remembered-evicted-path set backing the
#: ``evicted_reinserted`` counter (best-effort: overflowing resets it)
_EVICTED_KEYS_CAP = 4096


class _Node:
    """One cached page: ``key`` is its page_size-token run, ``page`` the
    physical page id holding that run's K/V. ``refs`` mirrors the device
    ``page_ref`` entry (active slots reading this page); ``last_used`` is
    the LRU clock tick of the last match that walked through it."""

    __slots__ = ("key", "page", "parent", "children", "refs", "last_used")

    def __init__(self, key, page: int, parent: Optional["_Node"]):
        self.key = key
        self.page = page
        self.parent = parent
        self.children = {}
        self.refs = 0
        self.last_used = 0


class PrefixCache:
    """Host-side radix tree naming pool pages by their token-run prefix.

    Pure bookkeeping — every device mutation (refcounts, stack pushes,
    block-table rows) goes through the ``kv_pool`` ops the scheduler
    jits; this class decides WHICH pages to share, keep, and evict."""

    def __init__(self, page_size: int,
                 metrics_labels: Optional[dict] = None):
        if page_size < 1:
            raise ValueError(f"page_size must be >= 1, got {page_size}")
        self.page_size = page_size
        self.root = _Node(key=None, page=-1, parent=None)
        self._nodes: set = set()
        self._tick = 0
        # eviction-churn observability (docs/observability.md): the
        # paths recently evicted (so a RE-insertion of an evicted path —
        # the thrash signature — is distinguishable from first-time
        # growth), evictions accumulated since the last retirement, and
        # the rolling evictions-per-retirement window behind the
        # ``prefix_cache.churn`` gauge
        self._evicted_keys: set = set()
        self._churn_window: deque = deque(maxlen=CHURN_WINDOW)
        self._evictions_since_retire = 0
        # label set for the cache's gauges/counters (the engine passes
        # its ``engine`` label so two caches never clobber one family)
        self._metrics_labels = (dict(metrics_labels)
                                if metrics_labels else None)

    # --- introspection ------------------------------------------------------

    def __len__(self) -> int:
        """Number of cached pages (= tree nodes, root excluded)."""
        return len(self._nodes)

    def _observe(self) -> None:
        """Refresh the residency gauge (``prefix_cache.pages``); called on
        every tree mutation — host-side dict math, no device traffic."""
        metrics.gauge("prefix_cache.pages",
                      labels=self._metrics_labels).set(len(self._nodes))

    def pages(self) -> List[int]:
        """Physical page ids the cache currently holds (order arbitrary)."""
        return [n.page for n in self._nodes]

    def _page_key(self, tokens, j: int):
        ps = self.page_size
        return tuple(int(t) for t in tokens[j * ps:(j + 1) * ps])

    def _path_hash(self, node: _Node) -> int:
        """Process-stable identity of a node's full token path (root →
        node) — how a re-insertion of a previously EVICTED path is
        recognized (the churn signature; a fresh path is just growth)."""
        return hash(self._path_keys(node))

    def _path_keys(self, node: _Node):
        """A node's full token path (root → node) as the tuple of its
        ancestors' page keys — the radix-node identity the host tier
        (``serving/host_tier.py``) files demoted pages under: token
        content, not physical page id, so defrag/realloc never
        invalidates a tier entry."""
        parts = []
        while node is not None and node.key is not None:
            parts.append(node.key)
            node = node.parent
        return tuple(reversed(parts))

    # --- admission ----------------------------------------------------------

    def match(self, prompt) -> List[_Node]:
        """Longest cached full-page prefix of ``prompt``: the node path,
        shallowest first (``[n.page for n in path]`` is the block-table
        prefix). Capped at ``(len(prompt) - 1) // page_size`` pages so the
        admission always prefills >= 1 token — the tail forward's
        last-position logits are what seed the first sampled token. Bumps
        the LRU clock along the path. Does NOT take references — call
        ``acquire`` once the admission commits (and nothing if it defers)."""
        prompt = np.asarray(prompt).reshape(-1)
        cap = max(int(prompt.shape[0]) - 1, 0) // self.page_size
        self._tick += 1
        path: List[_Node] = []
        node = self.root
        for j in range(cap):
            child = node.children.get(self._page_key(prompt, j))
            if child is None:
                break
            child.last_used = self._tick
            path.append(child)
            node = child
        return path

    def acquire(self, nodes: Sequence[_Node]) -> None:
        """Pin matched nodes for an admitted request (host mirror of the
        ``page_ref`` +1 that ``kv_pool.alloc_slot_shared`` applies)."""
        for n in nodes:
            n.refs += 1

    def release(self, nodes: Sequence[_Node]) -> None:
        """Undo ``acquire`` for a request that never got a device-side
        footprint (admission deferred after matching)."""
        for n in nodes:
            n.refs -= 1

    # --- retirement ---------------------------------------------------------

    def release_and_insert(self, tokens, written: int,
                           matched: Sequence[_Node], row,
                           ) -> np.ndarray:
        """Retire a request: drop its references on the matched prefix and
        move its newly-written full pages into the tree.

        ``tokens``: the request's WRITTEN token sequence (prompt followed
        by the generated tokens whose K/V actually landed in the pool);
        ``written``: its length — only full pages (``written //
        page_size``) are cacheable, the partial boundary page frees.
        ``matched``: the node path ``match`` returned at admission (their
        pages are the row's leading shared entries). ``row``: the slot's
        block-table row (host copy) — entry ``j`` holds the physical page
        for positions ``[j*ps, (j+1)*ps)``.

        Returns the bool keep mask for ``kv_pool.release_slot``: True
        entries stay cache property (the shared prefix + newly inserted
        pages), False entries return to the free stack (the partial tail,
        the preallocated-but-unused pages, and duplicates — pages whose
        key a concurrently-retired twin already inserted)."""
        row = np.asarray(row).reshape(-1)
        m = len(matched)
        n_cache = int(written) // self.page_size
        keep = np.zeros(row.shape[0], dtype=bool)
        keep[:m] = True                  # shared pages stay with the cache
        node = matched[-1] if matched else self.root
        self._tick += 1
        reinserted = 0
        for j in range(m, n_cache):
            key = self._page_key(tokens, j)
            child = node.children.get(key)
            if child is None:
                child = _Node(key=key, page=int(row[j]), parent=node)
                child.last_used = self._tick
                node.children[key] = child
                self._nodes.add(child)
                keep[j] = True           # ownership transfers to the cache
                pk = self._path_hash(child)
                if pk in self._evicted_keys:
                    # the churn signature: this exact path was evicted
                    # earlier and is now being recomputed + re-cached
                    self._evicted_keys.discard(pk)
                    reinserted += 1
            # else: a twin inserted this run first — our copy is a
            # duplicate and frees (keep[j] stays False); continue the walk
            # under the canonical node so deeper pages chain correctly
            node = child
        self.release(matched)
        inserted = int(keep[m:n_cache].sum())
        metrics.counter("prefix_cache.inserted_pages",
                        labels=self._metrics_labels).inc(inserted)
        metrics.counter("prefix_cache.duplicate_pages",
                        labels=self._metrics_labels).inc(
            (n_cache - m) - inserted)
        if reinserted:
            metrics.counter("prefix_cache.evicted_reinserted",
                            labels=self._metrics_labels).inc(reinserted)
        # churn = evictions per retirement over the rolling window: ~0
        # in steady state, >= 1 when every admission cycle evicts some
        # other tenant's pages (the eviction-churn scenario's gauge)
        self._churn_window.append(self._evictions_since_retire)
        self._evictions_since_retire = 0
        metrics.gauge("prefix_cache.churn",
                      labels=self._metrics_labels).set(
            sum(self._churn_window) / len(self._churn_window))
        self._observe()
        return keep

    # --- eviction -----------------------------------------------------------

    def evict(self, n: int, *, sink=None) -> List[int]:
        """Evict up to ``n`` pages — LRU first, leaves only, refcount-0
        only — removing their nodes and returning the physical page ids
        for ``kv_pool.evict_pages``. Evicting a leaf can expose its parent
        as the next candidate, so candidates heap by ``last_used`` and a
        parent enters the heap the moment its last child leaves —
        O((candidates + n) log candidates), no per-victim rescans. Pinned
        (refcount > 0) or interior pages never leave.

        ``sink``: optional ``sink(path_keys, page)`` callback invoked per
        victim BEFORE its page id is returned for the free-stack push —
        the host tier's demote hook (the frontend dispatches the page
        gather against these ids first, so the device-stream copy reads
        the page before any re-allocation can overwrite it). ``path_keys``
        is the victim's full root→node token path (its tier identity)."""
        out: List[int] = []
        heap = [(nd.last_used, id(nd), nd) for nd in self._nodes
                if not nd.children and nd.refs == 0]
        heapq.heapify(heap)
        while heap and len(out) < n:
            _, _, victim = heapq.heappop(heap)
            if (victim not in self._nodes or victim.children
                    or victim.refs != 0):
                continue                 # stale entry (state moved on)
            parent = victim.parent
            # remember the evicted PATH (victim.parent stays linked, so
            # the walk still works after the detach below) — bounded:
            # overflow resets the set, trading a few missed reinsert
            # counts for O(1) memory
            if len(self._evicted_keys) >= _EVICTED_KEYS_CAP:
                self._evicted_keys.clear()
            self._evicted_keys.add(self._path_hash(victim))
            if sink is not None:
                sink(self._path_keys(victim), victim.page)
            del parent.children[victim.key]
            self._nodes.remove(victim)
            out.append(victim.page)
            if (parent is not self.root and not parent.children
                    and parent.refs == 0):
                heapq.heappush(heap, (parent.last_used, id(parent), parent))
        self._evictions_since_retire += len(out)
        metrics.counter("prefix_cache.evicted_pages",
                        labels=self._metrics_labels).inc(len(out))
        self._observe()
        return out

    # --- promotion (host tier -> tree) --------------------------------------

    def insert_promoted(self, matched: Sequence[_Node], key,
                        page: int) -> _Node:
        """Graft one PROMOTED page under the matched path: the host tier
        held this key's bytes, the frontend scattered them into freshly
        popped page ``page`` (``kv_pool.promote_pages``), and the node
        now names it exactly as if the page had never left — refcount 0
        until the admission ``acquire``s the extended path. The caller
        (the frontend's promote walk, which runs strictly between
        ``match`` and ``acquire`` on the single pump thread) guarantees
        ``key`` is not already a child — ``match`` just proved the walk
        ended above it. Promotion is NOT a churn re-insert: the path came
        back without recompute, so its evicted-path marker just clears."""
        parent = matched[-1] if matched else self.root
        assert key not in parent.children, \
            "promote collided with a live child (match should have hit it)"
        node = _Node(key=key, page=int(page), parent=parent)
        node.last_used = self._tick
        parent.children[key] = node
        self._nodes.add(node)
        self._evicted_keys.discard(self._path_hash(node))
        metrics.counter("prefix_cache.promoted_pages",
                        labels=self._metrics_labels).inc()
        self._observe()
        return node

    # --- maintenance --------------------------------------------------------

    def remap(self, new_idx) -> None:
        """Follow a ``kv_pool.defrag_map`` compaction: rewrite every
        node's physical page through ``new_idx[old_page] = new_page``. The
        scheduler passes the cache's pages as ``extra_live``, so every
        node's page survived the compaction by construction."""
        new_idx = np.asarray(new_idx)
        for node in self._nodes:
            node.page = int(new_idx[node.page])
