"""Admission policy for the serving front-end: priorities, deadlines,
preemption victims.

The policy is pure host-side decision logic — it never touches the pool
or the device. ``ServingFrontend`` (``serving/frontend.py``) consults it
at every sync boundary to (a) order the pending queue, (b) decide whether
a blocked request justifies preempting a running one, and (c) pick the
victim. Keeping the three decisions in one small object makes the
scheduling discipline swappable (tests inject aggressive variants; a
deployment can subclass) without touching the pump.

Semantics (documented for operators in ``docs/frontend.md``):

- **priority** — larger int = more important. The pending queue is served
  highest-priority first; FIFO (arrival order) inside a priority class.
  Priorities are strict for *ordering* but only preemption (below) lets a
  late high-priority arrival displace work already running.
- **deadline_ms** — a TTFT service-level objective: the request should
  receive its first token within ``deadline_ms`` of ``arrival_time``.
  Deadlines break ties *within* a priority class (earliest deadline
  first) and arm preemption: a request that would otherwise sit blocked
  past its deadline may evict lower-priority running work. A missed
  deadline does not drop the request — it is still served, and the miss
  is counted (``serving.deadline_misses``).
- **preemption** — triggered only when a strictly-higher-priority request
  is blocked (no vacant slot, or not enough free pages) AND the policy
  says it cannot wait: its deadline is within ``preempt_margin_ms`` of
  now (or already past), or ``preempt_on_priority`` is set (preempt on
  priority alone, deadline or not). The victim is always the
  lowest-priority active request (ties: the most recently admitted one —
  the least sunk decode work); a victim is never preempted for an equal-
  or lower-priority candidate, so preempt/resume cannot ping-pong.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Dict, Optional, Tuple

__all__ = ["PriorityDeadlinePolicy"]


@dataclasses.dataclass
class PriorityDeadlinePolicy:
    """Priority-then-EDF admission order with deadline-armed preemption.

    ``preemption``: master switch — False degrades to pure queue ordering
    (a blocked high-priority request waits for a natural retirement).
    ``preempt_margin_ms``: how far ahead of a blocked request's deadline
    the policy acts; 0 preempts only once the deadline is already lost,
    a large margin preempts as soon as the request is blocked (tests and
    latency-critical tiers use this).
    ``preempt_on_priority``: preempt for any strictly-higher-priority
    blocked request even without a deadline — the most aggressive
    setting, used by the forced-preemption bench workload.
    ``slo_window_s``: the rolling window over which the frontend's
    ``serving.slo_burn`` gauge reports the SLO miss RATE (TTFT-deadline
    and TPOT-SLO misses over SLO-carrying retirements) — the policy owns
    the deadline semantics, so it owns the burn-rate horizon too.
    """

    preemption: bool = True
    preempt_margin_ms: float = 0.0
    preempt_on_priority: bool = False
    slo_window_s: float = 60.0

    # -- queue ordering ------------------------------------------------------

    def sort_key(self, entry, now: float) -> Tuple:
        """Total order over pending entries: higher priority first, then
        earliest deadline, then arrival time, then submission sequence
        (a stable FIFO tiebreak for identical clocks)."""
        deadline = entry.deadline_at if entry.deadline_at is not None \
            else math.inf
        return (-entry.priority, deadline, entry.arrival, entry.seq)

    # -- preemption ----------------------------------------------------------

    def at_risk(self, entry, now: float) -> bool:
        """True when ``entry`` (pending, blocked) is inside its preempt
        margin: waiting any longer risks (or has already caused) a
        deadline miss."""
        if entry.deadline_at is None:
            return False
        return now + self.preempt_margin_ms * 1e-3 >= entry.deadline_at

    def wants_preempt(self, candidate, now: float) -> bool:
        """Should a blocked ``candidate`` displace running work at all?
        (Victim eligibility is ``select_victim``'s call.)"""
        if not self.preemption:
            return False
        return self.preempt_on_priority or self.at_risk(candidate, now)

    def select_victim(self, candidate, active: Dict[int, object],
                      now: float) -> Optional[int]:
        """The slot to preempt for ``candidate``, or None. Only a
        strictly-lower-priority victim qualifies (equal priority never
        preempts — no ping-pong); among those, the lowest priority, and
        inside that class the most recently admitted (least sunk decode
        progress, mirroring vLLM's last-come-first-preempted)."""
        best_slot, best_key = None, None
        for slot, entry in active.items():
            if entry.priority >= candidate.priority:
                continue
            key = (entry.priority, -entry.seq)
            if best_key is None or key < best_key:
                best_slot, best_key = slot, key
        return best_slot
