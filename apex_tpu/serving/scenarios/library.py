"""The named scenario catalog — one registry, ``--list``-able.

Every entry is a factory ``(seed) -> ScenarioSpec`` registered under a
stable name; ``scenario_spec(name, seed, **overrides)`` builds the spec
and applies top-level ``dataclasses.replace`` overrides (how the bench
and tests scale a scenario up or down without forking its definition).
Sizes here are deliberately tiny-model/CPU-tier: a scenario is a
workload SHAPE + SLO harness, reproducible in tier-1 time — on-chip
throughput numbers stay ``tpu_decode_bench.py``'s business.

The catalog (docs/scenarios.md has the prose):

- ``steady-poisson`` — the baseline: memoryless arrivals, lognormal
  lengths, one tenant, no SLOs. The sanity row every other scenario is
  read against.
- ``burst-storm`` — on/off Markov-modulated arrivals with TTFT
  deadlines into few slots: queueing spikes, deadline misses, and the
  policy's EDF ordering under pressure.
- ``long-tail-lengths`` — Zipf prompt AND output lengths: a few huge
  requests among many small ones (continuous batching's reason to
  exist; the step-savings and occupancy counters tell the story).
- ``multi-tenant-shared-prefix`` — three tenants with distinct system
  prompts and distinct priority/deadline/TPOT-SLO profiles contending
  for one radix cache: per-tenant SLO splits + cross-request hit rate.
- ``eviction-churn`` — the adversary: more cacheable header pages than
  the pool holds, so admissions evict each other's headers and the tree
  thrashes (``prefix_cache.churn`` / ``evicted_reinserted`` light up).
- ``host-tier-churn`` — eviction-churn with a host-RAM spill tier
  under the same thrash-sized pool (``EngineSpec.host_tier_bytes``):
  churned hits promote instead of re-prefilling, and the report's
  ``host_tier`` block banks the tier-on-vs-off hit-rate A/B (strictly
  positive delta is the acceptance bar).
- ``priority-flood`` — a low-priority flood pinning every slot while a
  high-priority deadline stream arrives: preempt-and-spill under
  ``preempt_on_priority``, priority-inversion bounded.
- ``tp-shared-prefix`` — the multi-tenant radix-cache workload replayed
  through the tp=2 TENSOR-PARALLEL engine (``serving/tp.py``): hits,
  SLO splits, and contention must compose with the head-sharded pool.
- ``windowed-llama`` — sliding-window Llama on the PAGED path (the band
  rides the paged kernel, dead pages drop at sync boundaries): long
  generations at O(window) live pages per slot.
- ``bench-mixed-length`` / ``bench-shared-prefix`` — the decode bench's
  two original workloads, now defined here (``tpu_decode_bench.py``
  materializes these instead of carrying inline generators).
- ``preemption-storm`` — the ROADMAP-5 adversary: a rapid
  high-priority deadline stream over one slot forces repeated
  preempt/resume cycles on a long-running bulk request; the recompile
  watcher pins the resume compile-key set (no ``compile_storm`` event,
  bounded ``jit.compiles``).
- ``chaos-replica-kill`` — replicated serving (``serving/router.py``)
  with a seeded mid-decode replica kill (``serving/faults.py``): every
  in-flight request must re-home to the survivor token-identically
  (the greedy-identity amplifier proves recovery corrupts nothing);
  the kill triggers the flight recorder and the report banks the
  federated ``fleet`` block (docs/observability.md "Fleet plane").
- ``chaos-pump-stall`` — a wedged-but-alive replica (injected pump
  stalls): latency, not death — nothing may hang, fail over, or leak.
- ``chaos-slow-reader`` — the replay driven over real localhost HTTP
  (``EngineSpec(http=True)``, scenarios/http_driver.py): clients stop
  reading their SSE streams mid-generation, unconsumed tokens cross the
  frontend's ``backpressure_window``, the slot spills into the radix
  cache, and every stream still completes token-identically when the
  reader resumes — the no-pin contract, banked (``http.
  backpressure_spills``).
- ``chaos-disconnect-storm`` — the HTTP replay under network chaos:
  several clients drop their sockets for real mid-stream and two tear
  their connections mid-request (RST) then retry; the server must
  cancel, free every page, and keep serving — surviving outputs
  token-identical, dropped ones exact prefixes.
- ``router-affinity-ab`` — the multi-tenant workload over 2 replicas,
  replayed under affinity routing AND round-robin on the same trace:
  the aggregate prefix hit-rate delta is the banked proof affinity
  routing earns its keep.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Dict, List

from apex_tpu.serving.faults import FaultSpec
from apex_tpu.serving.scenarios.runner import EngineSpec, ScenarioSpec
from apex_tpu.serving.scenarios.tenants import Tenant, churn_tenants
from apex_tpu.serving.scenarios.traces import Arrival, Lengths

__all__ = ["SCENARIOS", "register", "scenario_names", "scenario_spec"]

SCENARIOS: Dict[str, Callable[[int], ScenarioSpec]] = {}


def register(name: str):
    def deco(fn: Callable[[int], ScenarioSpec]):
        SCENARIOS[name] = fn
        return fn
    return deco


def scenario_names() -> List[str]:
    return sorted(SCENARIOS)


def scenario_spec(name: str, seed: int = 0,
                  **overrides) -> ScenarioSpec:
    """Build a catalog scenario at ``seed``, with optional top-level
    field overrides (``n_requests=``, ``engine=``, ``prompt_lens=``,
    ...)."""
    if name not in SCENARIOS:
        raise KeyError(f"unknown scenario {name!r}; have "
                       f"{scenario_names()}")
    spec = SCENARIOS[name](seed)
    return dataclasses.replace(spec, **overrides) if overrides else spec


@register("steady-poisson")
def _steady_poisson(seed: int) -> ScenarioSpec:
    return ScenarioSpec(
        name="steady-poisson", seed=seed, n_requests=20,
        arrival=Arrival(kind="poisson", rate_rps=400.0),
        prompt_lens=Lengths(kind="lognormal", mean=20.0, sigma=0.5,
                            lo=4, hi=48),
        output_lens=Lengths(kind="uniform", lo=4, hi=10),
        tenants=(Tenant("default"),),
        engine=EngineSpec(model="gpt2-tiny", num_slots=3, page_size=8,
                          prefix_cache=False),
        description="memoryless open-loop baseline, one tenant")


@register("burst-storm")
def _burst_storm(seed: int) -> ScenarioSpec:
    return ScenarioSpec(
        name="burst-storm", seed=seed, n_requests=24,
        arrival=Arrival(kind="bursty", burst_rate_rps=2000.0,
                        idle_rate_rps=40.0, mean_burst_s=0.015,
                        mean_idle_s=0.06),
        prompt_lens=Lengths(kind="lognormal", mean=16.0, sigma=0.5,
                            lo=4, hi=40),
        output_lens=Lengths(kind="uniform", lo=4, hi=10),
        tenants=(Tenant("bursty", deadline_ms=250.0),),
        engine=EngineSpec(model="gpt2-tiny", num_slots=2, page_size=8,
                          prefix_cache=False),
        description="on/off MMPP arrivals + TTFT deadlines into 2 slots")


@register("long-tail-lengths")
def _long_tail(seed: int) -> ScenarioSpec:
    return ScenarioSpec(
        name="long-tail-lengths", seed=seed, n_requests=20,
        arrival=Arrival(kind="poisson", rate_rps=300.0),
        prompt_lens=Lengths(kind="zipf", zipf_a=1.4, lo=4, hi=80),
        output_lens=Lengths(kind="zipf", zipf_a=1.6, lo=2, hi=32),
        tenants=(Tenant("default"),),
        engine=EngineSpec(model="gpt2-tiny", num_slots=3, page_size=8,
                          prefix_cache=False),
        description="Zipf prompt+output mix: few huge, many small")


@register("multi-tenant-shared-prefix")
def _multi_tenant(seed: int) -> ScenarioSpec:
    ps = 8
    return ScenarioSpec(
        name="multi-tenant-shared-prefix", seed=seed, n_requests=24,
        arrival=Arrival(kind="poisson", rate_rps=400.0),
        prompt_lens=Lengths(kind="lognormal", mean=10.0, sigma=0.5,
                            lo=2, hi=24),
        output_lens=Lengths(kind="uniform", lo=4, hi=10),
        tenants=(
            Tenant("free", weight=2.0, system_prompt_tokens=2 * ps),
            Tenant("pro", weight=1.0, system_prompt_tokens=4 * ps,
                   priority=2, deadline_ms=400.0),
            Tenant("batch", weight=1.0, system_prompt_tokens=2 * ps,
                   tpot_slo_ms=500.0),
        ),
        engine=EngineSpec(model="gpt2-tiny", num_slots=3, page_size=ps,
                          prefix_cache=True),
        description="3 tenants, distinct headers + SLO profiles, one "
                    "radix cache")


@register("eviction-churn")
def _eviction_churn(seed: int) -> ScenarioSpec:
    ps = 8
    # 8 tenants x 4 header pages = 32 cacheable pages vs a 23-page pool:
    # the tree cannot hold every header and admissions evict each other
    return ScenarioSpec(
        name="eviction-churn", seed=seed, n_requests=32,
        arrival=Arrival(kind="closed", users=4, think_ms=4.0),
        prompt_lens=Lengths(kind="uniform", lo=1, hi=8),
        output_lens=Lengths(kind="uniform", lo=2, hi=6),
        tenants=churn_tenants(8, 4, ps),
        engine=EngineSpec(model="gpt2-tiny", num_slots=2, page_size=ps,
                          prefix_cache=True, num_pages=24),
        description="adversarial header set > pool capacity: radix "
                    "thrash")


@register("host-tier-churn")
def _host_tier_churn(seed: int) -> ScenarioSpec:
    ps = 8
    # the eviction-churn adversary with a host-RAM spill tier under the
    # same thrash-sized pool: every churned header eviction demotes and
    # every revisit promotes, so the banked host_tier block's
    # tier-on-vs-off hit-rate delta must be strictly positive
    return ScenarioSpec(
        name="host-tier-churn", seed=seed, n_requests=32,
        arrival=Arrival(kind="closed", users=4, think_ms=4.0),
        prompt_lens=Lengths(kind="uniform", lo=1, hi=8),
        output_lens=Lengths(kind="uniform", lo=2, hi=6),
        tenants=churn_tenants(8, 4, ps),
        engine=EngineSpec(model="gpt2-tiny", num_slots=2, page_size=ps,
                          prefix_cache=True, num_pages=24,
                          host_tier_bytes=1 << 24),
        description="eviction-churn with a host spill tier: churned "
                    "hits promote instead of re-prefilling")


@register("priority-flood")
def _priority_flood(seed: int) -> ScenarioSpec:
    return ScenarioSpec(
        name="priority-flood", seed=seed, n_requests=24,
        arrival=Arrival(kind="poisson", rate_rps=600.0),
        prompt_lens=Lengths(kind="uniform", lo=8, hi=24),
        output_lens=Lengths(kind="uniform", lo=8, hi=16),
        tenants=(
            Tenant("flood", weight=5.0),
            Tenant("urgent", weight=1.0, priority=5, deadline_ms=60.0),
        ),
        engine=EngineSpec(model="gpt2-tiny", num_slots=2, page_size=8,
                          prefix_cache=True, preempt_on_priority=True),
        description="low-priority flood vs high-priority deadline "
                    "stream: preempt-and-spill")


@register("tp-shared-prefix")
def _tp_shared_prefix(seed: int) -> ScenarioSpec:
    ps = 8
    # the multi-tenant radix-cache workload on the TENSOR-PARALLEL
    # engine (serving/tp.py, docs/tp_serving.md): three tenants with
    # distinct headers + SLO profiles replayed through a tp=2 mesh —
    # prefix hits, preemption-free contention, and per-tenant SLO
    # splits must all compose with the head-sharded pool. Needs >= 2
    # devices (tests/CI force 8 CPU devices; the CLI raises otherwise).
    return ScenarioSpec(
        name="tp-shared-prefix", seed=seed, n_requests=16,
        arrival=Arrival(kind="poisson", rate_rps=400.0),
        prompt_lens=Lengths(kind="lognormal", mean=10.0, sigma=0.5,
                            lo=2, hi=24),
        output_lens=Lengths(kind="uniform", lo=4, hi=10),
        tenants=(
            Tenant("free", weight=2.0, system_prompt_tokens=2 * ps),
            Tenant("pro", weight=1.0, system_prompt_tokens=4 * ps,
                   priority=2, deadline_ms=400.0),
            Tenant("batch", weight=1.0, system_prompt_tokens=2 * ps,
                   tpot_slo_ms=500.0),
        ),
        engine=EngineSpec(model="gpt2-tiny", num_slots=3, page_size=ps,
                          prefix_cache=True, tensor_parallel=2),
        description="multi-tenant shared-prefix replay through the "
                    "tp=2 tensor-parallel engine")


@register("windowed-llama")
def _windowed_llama(seed: int) -> ScenarioSpec:
    return ScenarioSpec(
        name="windowed-llama", seed=seed, n_requests=10,
        arrival=Arrival(kind="poisson", rate_rps=300.0),
        prompt_lens=Lengths(kind="uniform", lo=8, hi=32),
        output_lens=Lengths(kind="uniform", lo=24, hi=40),
        tenants=(Tenant("default"),),
        engine=EngineSpec(model="llama-tiny-windowed", num_slots=2,
                          page_size=8, sync_every=2,
                          prefix_cache=False),
        description="sliding-window Llama on the paged path: "
                    "generations past the window drop dead pages")


@register("preemption-storm")
def _preemption_storm(seed: int) -> ScenarioSpec:
    ps = 16
    # ONE slot, a long-running bulk stream, and a rapid deadline-armed
    # urgent stream: every urgent arrival preempts the bulk victim,
    # which resumes (spill -> cache-hit re-admission) when the urgent
    # request retires — many preempt/resume cycles per replay. The
    # page size is deliberately LARGE and the urgent bursts short, so
    # the victim's written length crosses few page boundaries and the
    # resume compile-key set (t_start values) stays small — the
    # recompile-watcher pin (no compile_storm, bounded jit.compiles)
    # binds exactly that design rule (docs/frontend.md Limits).
    # arrivals are PACED against the tiny model's CPU decode step
    # (~5-15 ms): a bulk long-runner must actually be decoding when the
    # next urgent request lands, or priority ordering alone would serve
    # the queue and nothing would ever preempt
    return ScenarioSpec(
        name="preemption-storm", seed=seed, n_requests=16,
        arrival=Arrival(kind="poisson", rate_rps=5.0),
        prompt_lens=Lengths(kind="uniform", lo=8, hi=14),
        output_lens=Lengths(kind="uniform", lo=24, hi=32),
        tenants=(
            Tenant("bulk", weight=1.0, output_tokens=40),
            Tenant("urgent", weight=2.0, priority=5,
                   deadline_ms=10000.0, output_tokens=2),
        ),
        engine=EngineSpec(model="gpt2-tiny", num_slots=1, page_size=ps,
                          prefix_cache=True, preempt_on_priority=True),
        description="repeated preempt/resume cycles on one slot; the "
                    "resume compile-key set must stay bounded")


@register("chaos-replica-kill")
def _chaos_replica_kill(seed: int) -> ScenarioSpec:
    ps = 8
    # 2 replicas, one killed mid-decode at its 3rd pump iteration:
    # every request it held (active, pending, mid-stream) must re-home
    # to the survivor with its generated-so-far tokens folded into the
    # resume prompt — greedy outputs identical to an unfailed run (the
    # check amplifier), zero hung handles, zero leaked pages. The kill
    # also exercises the fleet plane: the report banks the federated
    # ``fleet`` block and the death triggers the flight recorder, so
    # the CI round banks FLEET_/FLIGHT_ artifacts off this scenario
    # (``--fleet``/``--flight``; docs/observability.md "Fleet plane")
    return ScenarioSpec(
        name="chaos-replica-kill", seed=seed, n_requests=12,
        arrival=Arrival(kind="poisson", rate_rps=600.0),
        prompt_lens=Lengths(kind="uniform", lo=6, hi=20),
        output_lens=Lengths(kind="uniform", lo=6, hi=12),
        tenants=(
            Tenant("alpha", weight=1.0, system_prompt_tokens=2 * ps),
            Tenant("beta", weight=1.0, system_prompt_tokens=2 * ps),
        ),
        engine=EngineSpec(model="gpt2-tiny", num_slots=2, page_size=ps,
                          prefix_cache=True, replicas=2),
        faults=(FaultSpec(kind="kill_replica", replica=0, at=3),),
        description="seeded mid-decode replica kill: recovery must be "
                    "token-exact on the survivor")


@register("chaos-pump-stall")
def _chaos_pump_stall(seed: int) -> ScenarioSpec:
    # a wedged-but-alive replica: the pump sleeps 20 ms for 4
    # iterations — pure latency; nothing may die, fail over, or leak
    return ScenarioSpec(
        name="chaos-pump-stall", seed=seed, n_requests=10,
        arrival=Arrival(kind="poisson", rate_rps=600.0),
        prompt_lens=Lengths(kind="uniform", lo=6, hi=16),
        output_lens=Lengths(kind="uniform", lo=4, hi=8),
        tenants=(Tenant("default"),),
        engine=EngineSpec(model="gpt2-tiny", num_slots=2, page_size=8,
                          prefix_cache=False, replicas=2),
        faults=(FaultSpec(kind="pump_stall", replica=1, at=2, count=4,
                          delay_ms=20.0),),
        description="injected pump stalls on one replica: latency, "
                    "not death")


@register("chaos-slow-reader")
def _chaos_slow_reader(seed: int) -> ScenarioSpec:
    # over-the-wire replay with stalled readers: requests 0 and 1 read
    # two tokens then stop reading for 700 ms with the socket open.
    # The padded SSE frames + tiny kernel buffers (sndbuf/SO_RCVBUF)
    # make the TCP window fill within a few events, writer.drain()
    # parks, acks stop, and the pump — still generating — crosses the
    # 6-token backpressure window: the slot spills into the radix cache
    # instead of pinning pages for a socket. When the reader resumes,
    # the stream completes token-identically (the identity amplifier
    # proves the spill/resume cycle corrupted nothing). Outputs are
    # pinned long (48 tokens) so the stall always lands mid-generation.
    return ScenarioSpec(
        name="chaos-slow-reader", seed=seed, n_requests=4,
        arrival=Arrival(kind="poisson", rate_rps=200.0),
        prompt_lens=Lengths(kind="uniform", lo=6, hi=12),
        output_lens=Lengths(kind="uniform", lo=48, hi=48),
        tenants=(Tenant("default", output_tokens=48),),
        engine=EngineSpec(model="gpt2-tiny", num_slots=2, page_size=8,
                          prefix_cache=True, http=True,
                          backpressure_window=6, sse_pad_bytes=2048,
                          sndbuf=4096),
        faults=(FaultSpec(kind="slow_reader", at=2, count=2,
                          delay_ms=700.0),),
        description="stalled SSE readers cross the backpressure window:"
                    " spill, resume, token-identical completion")


@register("chaos-disconnect-storm")
def _chaos_disconnect_storm(seed: int) -> ScenarioSpec:
    # network chaos on the HTTP surface: requests 0-3 drop their
    # sockets for real (shutdown(SHUT_RDWR)) after reading 3 tokens,
    # and requests 0-1 additionally tear their submit mid-request with
    # an RST (SO_LINGER 0) before retrying on a fresh connection. The
    # server must notice every drop, cancel at the next sync boundary,
    # free the pages (the driver's leak check), and keep streaming the
    # survivors untouched. Outputs are pinned at 24 tokens so the drop
    # always lands mid-generation; the greedy/scheduling checks accept
    # exact PREFIXES for the dropped ids (runner._net_prefix_ids).
    return ScenarioSpec(
        name="chaos-disconnect-storm", seed=seed, n_requests=10,
        arrival=Arrival(kind="poisson", rate_rps=300.0),
        prompt_lens=Lengths(kind="uniform", lo=6, hi=16),
        output_lens=Lengths(kind="uniform", lo=24, hi=24),
        tenants=(Tenant("default", output_tokens=24),),
        engine=EngineSpec(model="gpt2-tiny", num_slots=3, page_size=8,
                          prefix_cache=True, http=True),
        faults=(FaultSpec(kind="client_disconnect", at=3, count=4),
                FaultSpec(kind="conn_reset", count=2)),
        description="mid-stream socket drops + torn submits: cancel, "
                    "free pages, survivors token-identical")


@register("router-affinity-ab")
def _router_affinity_ab(seed: int) -> ScenarioSpec:
    ps = 8
    # the multi-tenant radix-cache workload over TWO replicas, banked
    # both ways: affinity routing (tenant header -> one replica, its
    # cache warm) vs round-robin (headers smeared over both caches).
    # The aggregate hit-rate delta is the ledger-banked proof
    return ScenarioSpec(
        name="router-affinity-ab", seed=seed, n_requests=24,
        arrival=Arrival(kind="poisson", rate_rps=500.0),
        prompt_lens=Lengths(kind="lognormal", mean=10.0, sigma=0.5,
                            lo=2, hi=24),
        output_lens=Lengths(kind="uniform", lo=4, hi=8),
        tenants=(
            Tenant("free", weight=1.0, system_prompt_tokens=2 * ps),
            Tenant("pro", weight=1.0, system_prompt_tokens=4 * ps,
                   priority=2),
            Tenant("batch", weight=1.0, system_prompt_tokens=3 * ps),
            Tenant("edge", weight=1.0, system_prompt_tokens=2 * ps),
        ),
        engine=EngineSpec(model="gpt2-tiny", num_slots=2, page_size=ps,
                          prefix_cache=True, replicas=2,
                          compare_round_robin=True),
        description="affinity vs round-robin hit-rate A/B over 2 "
                    "replicas, same trace")


@register("bench-mixed-length")
def _bench_mixed_length(seed: int) -> ScenarioSpec:
    # tpu_decode_bench's original paged workload, catalogued: mixed
    # prompt/output lengths so continuous batching beats lock-step
    # padding (the step-savings assert)
    return ScenarioSpec(
        name="bench-mixed-length", seed=seed, n_requests=8,
        arrival=Arrival(kind="poisson", rate_rps=500.0),
        prompt_lens=Lengths(kind="uniform", lo=8, hi=64),
        output_lens=Lengths(kind="uniform", lo=8, hi=24),
        tenants=(Tenant("default"),),
        engine=EngineSpec(model="gpt2-tiny", num_slots=3, page_size=8,
                          prefix_cache=False),
        description="the decode bench's mixed-length closed-loop "
                    "workload")


@register("bench-shared-prefix")
def _bench_shared_prefix(seed: int) -> ScenarioSpec:
    ps = 8
    return ScenarioSpec(
        name="bench-shared-prefix", seed=seed, n_requests=8,
        arrival=Arrival(kind="poisson", rate_rps=500.0),
        prompt_lens=Lengths(kind="uniform", lo=4, hi=16),
        output_lens=Lengths(kind="uniform", lo=6, hi=12),
        tenants=(Tenant("shared", system_prompt_tokens=4 * ps),),
        engine=EngineSpec(model="gpt2-tiny", num_slots=2, page_size=ps,
                          prefix_cache=True),
        description="the decode bench's shared-system-prompt workload")
