"""The pinned-schema ScenarioReport: per-tenant + aggregate SLO stats.

One scenario run produces one report dict with a FIXED shape (CI, the
perf ledger, and the tests all key into it — ``validate_report`` is the
contract check). Latency percentiles are computed from the span tracer's
per-request lifecycles (docs/observability.md) — exact percentiles over
this run's requests, the same source the frontend's run stats use — so
the per-tenant splits and the aggregate are consistent by construction.
Engine counters (hit rate, preemptions, evictions, window drops) come
from the frontend's ``stats()`` delta dict and are embedded verbatim
under ``engine`` for postmortems.

``python -m apex_tpu.obs.ledger --append --bench SCENARIOS_<tag>.json``
extracts ``scenario.<name>.ttft_ms_p95`` / ``tpot_ms_p95`` /
``deadline_miss_rate`` from the aggregate block and band-gates them like
the other wall-time metrics.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import numpy as np

__all__ = ["REPORT_SCHEMA", "SCENARIOS_SCHEMA", "AGGREGATE_FIELDS",
           "TENANT_FIELDS", "ROUTER_FIELDS", "HTTP_FIELDS",
           "HOST_TIER_FIELDS", "FLEET_FIELDS", "build_report",
           "validate_report"]

REPORT_SCHEMA = "apex-tpu/scenario-report/v1"
#: the multi-scenario CLI document wrapping one report per scenario
SCENARIOS_SCHEMA = "apex-tpu/scenarios/v1"
#: the ``--fleet`` sidecar document (per-scenario federated fleet
#: blocks). Write-only CI evidence — banked per round for human review,
#: nothing in-repo reads it back, hence no paired validator.
# tpu-lint: disable=contract-schema-unpinned -- write-only CI evidence
FLEET_DOC_SCHEMA = "apex-tpu/fleet/v1"

#: pinned aggregate keys — every report carries exactly these
AGGREGATE_FIELDS = (
    "ttft_ms_p50", "ttft_ms_p95", "tpot_ms_p50", "tpot_ms_p95",
    "queue_wait_ms_p50", "queue_wait_ms_p95",
    "deadline_requests", "deadline_misses", "deadline_miss_rate",
    "tpot_slo_misses", "preemptions", "resumes",
    "prefix_hit_rate", "prefill_tokens_skipped", "evicted_pages",
    "window_dropped_pages", "generated_tokens", "tokens_per_sec",
    "duration_s",
)

#: pinned per-tenant keys
TENANT_FIELDS = (
    "requests", "generated_tokens",
    "ttft_ms_p50", "ttft_ms_p95", "tpot_ms_p50", "tpot_ms_p95",
    "queue_wait_ms_p50", "queue_wait_ms_p95",
    "deadline_requests", "deadline_misses", "deadline_miss_rate",
)

#: pinned ``router`` block keys (present on replicated scenarios only;
#: the A/B keys ``round_robin_hit_rate``/``affinity_delta_hit_rate``
#: appear additionally under ``compare_round_robin``)
ROUTER_FIELDS = (
    "replicas", "replicas_alive", "routing",
    "failovers", "failover_requests", "failover_recovered",
    "failover_recovered_rate", "shed_requests", "migrations",
    "replica_deaths", "affinity_hit_rate",
)

#: pinned ``host_tier`` block keys (present on tiered scenarios only —
#: ``EngineSpec(host_tier_bytes > 0)``; the A/B keys come from the same
#: trace re-replayed with the tier off, docs/serving.md "Tiered KV
#: pool")
HOST_TIER_FIELDS = (
    "budget_bytes", "demotes", "promotes", "host_evicted_pages",
    "promote_hit_rate", "tier_on_hit_rate", "tier_off_hit_rate",
    "tier_delta_hit_rate",
)

#: pinned ``fleet`` block keys (present on replicated scenarios — the
#: router's federated observability block, ``router.fleet.block()``;
#: docs/observability.md "Fleet plane")
FLEET_FIELDS = (
    "replicas", "ttft_ms_p95", "tpot_ms_p95", "queue_depth",
    "slo_burn", "compile_storms", "scrape_age_s_max",
    "alerts_fired", "alert_firing", "per_replica",
)

#: pinned ``http`` block keys (present when the scenario replayed over
#: the wire — ``EngineSpec(http=True)``, scenarios/http_driver.py)
HTTP_FIELDS = (
    "streams", "tokens", "disconnects", "rejected", "errors",
    "conn_reset_retries", "slow_reader_stalls",
    "backpressure_spills", "free_pages_recovered",
)


def _pct(vals: Sequence[float], q: float) -> float:
    return float(np.percentile(np.asarray(vals, np.float64), q)) \
        if len(vals) else 0.0


def _latency_block(lifes: List[dict], missed: Dict[int, bool],
                   deadlined: Dict[int, bool]) -> dict:
    ttft = [lf["ttft_ms"] for lf in lifes if "ttft_ms" in lf]
    tpot = [lf["tpot_ms"] for lf in lifes if "tpot_ms" in lf]
    qw = [lf["queue_wait_ms"] for lf in lifes if "queue_wait_ms" in lf]
    n_dl = sum(1 for lf in lifes if deadlined.get(lf["request_id"]))
    n_miss = sum(1 for lf in lifes if missed.get(lf["request_id"]))
    return {
        "ttft_ms_p50": round(_pct(ttft, 50), 3),
        "ttft_ms_p95": round(_pct(ttft, 95), 3),
        "tpot_ms_p50": round(_pct(tpot, 50), 3),
        "tpot_ms_p95": round(_pct(tpot, 95), 3),
        "queue_wait_ms_p50": round(_pct(qw, 50), 3),
        "queue_wait_ms_p95": round(_pct(qw, 95), 3),
        "deadline_requests": n_dl,
        "deadline_misses": n_miss,
        "deadline_miss_rate": round(n_miss / max(n_dl, 1), 4),
    }


def build_report(spec, trace, outputs, stats: dict, tracer,
                 wall_s: float, checks: Optional[dict] = None,
                 router: Optional[dict] = None,
                 http: Optional[dict] = None,
                 host_tier: Optional[dict] = None,
                 fleet: Optional[dict] = None) -> dict:
    """Assemble the pinned-schema report for one replayed scenario.
    ``router`` is the replicated-scenario block (``ROUTER_FIELDS``) —
    failover/recovery facts and the affinity A/B; ``http`` the
    over-the-wire replay's block (``HTTP_FIELDS``); ``host_tier`` the
    tiered-pool block (``HOST_TIER_FIELDS``) — demote/promote facts and
    the tier-on/off A/B; ``fleet`` the router's federated
    observability block (``FLEET_FIELDS``, ``router.fleet.block()``);
    ``tracer`` may be the router's cross-replica
    lifecycle adapter (same ``lifecycle``/``spans`` surface as a
    :class:`~apex_tpu.obs.spans.SpanTracer`)."""
    events = trace.events
    lifes = [tracer.lifecycle(e.request_id) for e in events]
    # per-request deadline facts: carried by the trace (who had one) and
    # the tracer's deadline_miss instants (who missed it)
    deadlined = {e.request_id: e.deadline_ms is not None for e in events}
    missed = {e.request_id: any(s.name == "deadline_miss"
                                for s in tracer.spans(e.request_id))
              for e in events}
    gen_total = int(sum(np.asarray(o).shape[0] for o in outputs))

    aggregate = _latency_block(lifes, missed, deadlined)
    aggregate.update({
        "tpot_slo_misses": int(stats.get("tpot_slo_misses", 0)),
        "preemptions": int(stats.get("preemptions", 0)),
        "resumes": int(stats.get("resumes", 0)),
        "prefix_hit_rate": round(float(stats.get("prefix_hit_rate",
                                                 0.0)), 4),
        "prefill_tokens_skipped": int(stats.get("prefill_tokens_skipped",
                                                0)),
        "evicted_pages": int(stats.get("evicted_pages", 0)),
        "window_dropped_pages": int(stats.get("window_dropped_pages",
                                              0)),
        "generated_tokens": gen_total,
        "tokens_per_sec": round(gen_total / max(wall_s, 1e-9), 1),
        "duration_s": round(wall_s, 4),
    })

    per_tenant: Dict[str, dict] = {}
    for name in sorted({e.tenant for e in events}):
        ids = {e.request_id for e in events if e.tenant == name}
        t_lifes = [lf for lf in lifes if lf["request_id"] in ids]
        block = _latency_block(t_lifes, missed, deadlined)
        block["requests"] = len(ids)
        block["generated_tokens"] = int(sum(
            np.asarray(outputs[i]).shape[0] for i in range(len(events))
            if events[i].request_id in ids))
        per_tenant[name] = block

    report = {
        "schema": REPORT_SCHEMA,
        "scenario": spec.name,
        "seed": spec.seed,
        "model": spec.engine.model,
        "n_requests": len(events),
        "n_tenants": len(per_tenant),
        "trace_sha256": trace.sha256(),
        "aggregate": aggregate,
        "per_tenant": per_tenant,
        "engine": {k: v for k, v in sorted(stats.items())},
    }
    if router is not None:
        report["router"] = dict(router)
    if fleet is not None:
        report["fleet"] = dict(fleet)
    if http is not None:
        report["http"] = dict(http)
    if host_tier is not None:
        report["host_tier"] = dict(host_tier)
    if checks is not None:
        report["checks"] = dict(checks)
    return report


def validate_report(report: dict) -> None:
    """The schema pin: raise ``ValueError`` on any missing key (CI's
    smoke and the tests call this so the ledger extraction can rely on
    the shape)."""
    for key in ("schema", "scenario", "seed", "model", "n_requests",
                "n_tenants", "trace_sha256", "aggregate", "per_tenant",
                "engine"):
        if key not in report:
            raise ValueError(f"scenario report missing {key!r}")
    if report["schema"] != REPORT_SCHEMA:
        raise ValueError(f"unexpected report schema "
                         f"{report['schema']!r} != {REPORT_SCHEMA!r}")
    missing = [f for f in AGGREGATE_FIELDS
               if f not in report["aggregate"]]
    if missing:
        raise ValueError(f"aggregate block missing {missing}")
    for name, block in report["per_tenant"].items():
        t_missing = [f for f in TENANT_FIELDS if f not in block]
        if t_missing:
            raise ValueError(f"tenant {name!r} block missing "
                             f"{t_missing}")
    if "router" in report:
        r_missing = [f for f in ROUTER_FIELDS
                     if f not in report["router"]]
        if r_missing:
            raise ValueError(f"router block missing {r_missing}")
    if "fleet" in report:
        f_missing = [f for f in FLEET_FIELDS
                     if f not in report["fleet"]]
        if f_missing:
            raise ValueError(f"fleet block missing {f_missing}")
    if "http" in report:
        h_missing = [f for f in HTTP_FIELDS
                     if f not in report["http"]]
        if h_missing:
            raise ValueError(f"http block missing {h_missing}")
    if "host_tier" in report:
        ht_missing = [f for f in HOST_TIER_FIELDS
                      if f not in report["host_tier"]]
        if ht_missing:
            raise ValueError(f"host_tier block missing {ht_missing}")
