"""Multi-tenant workload profiles over one shared prefix cache.

Production serving is rarely one traffic class: N tenants — each with
its own system prompt, priority tier, and SLOs — contend for one engine
and ONE radix prefix cache. A :class:`Tenant` bundles the per-class
knobs; ``materialize`` (``runner.py``) samples each event's tenant by
``weight`` and prepends the tenant's deterministic system prompt, so a
tenant's requests share their header pages (cross-request hits) while
distinct tenants collide only in pool capacity.

The system prompt is a pure function of ``(scenario seed, tenant
name)`` — two runs, or two tenants that happen to share a name across
scenarios, regenerate identical headers, which is what makes cached-page
hits (and the eviction-churn adversary below) reproducible.

``churn_tenants`` builds the adversarial tenant set for the
``eviction-churn`` scenario: enough tenants, each with a long-enough
header, that the sum of cacheable header pages exceeds the pool — every
admission cycle then evicts some other tenant's header and re-inserts
its own, and the radix tree thrashes. The ``prefix_cache.churn`` gauge
and ``prefix_cache.evicted_reinserted`` counter are the first-class
signals of that state (docs/observability.md).
"""

from __future__ import annotations

import dataclasses
import hashlib
from typing import Optional, Sequence, Tuple

import numpy as np

__all__ = ["Tenant", "system_prompt", "assign_tenants", "churn_tenants"]


@dataclasses.dataclass(frozen=True)
class Tenant:
    """One traffic class: its shared header + scheduling/SLO profile.

    ``weight`` is the tenant's relative traffic share (sampling weight,
    not a hard quota); ``system_prompt_tokens`` the length of its shared
    header (0 = no shared prefix); ``priority``/``deadline_ms``/
    ``tpot_slo_ms`` stamp every request the tenant emits (the
    ``Request`` fields the policy and SLO accounting consume).
    ``output_tokens`` pins the tenant's requests to a FIXED output
    budget instead of the scenario's sampled ``output_lens`` — how
    adversaries pit a short-request tenant against a long-running one
    (the ``preemption-storm`` scenario's urgent-vs-bulk shape)."""

    name: str
    weight: float = 1.0
    system_prompt_tokens: int = 0
    priority: int = 0
    deadline_ms: Optional[float] = None
    tpot_slo_ms: Optional[float] = None
    output_tokens: Optional[int] = None


def system_prompt(tenant: Tenant, vocab_size: int,
                  seed: int) -> np.ndarray:
    """The tenant's deterministic shared header: seeded from
    ``(seed, sha256(name))`` so it depends on nothing but the scenario
    seed and the tenant's identity."""
    if tenant.system_prompt_tokens <= 0:
        return np.zeros((0,), np.int32)
    name_key = int.from_bytes(
        hashlib.sha256(tenant.name.encode()).digest()[:8], "big")
    rng = np.random.default_rng([seed & 0xFFFFFFFF, name_key])
    return rng.integers(0, vocab_size,
                        tenant.system_prompt_tokens).astype(np.int32)


def assign_tenants(tenants: Sequence[Tenant], n: int,
                   rng: np.random.Generator) -> np.ndarray:
    """Per-event tenant index, sampled by ``weight``."""
    if not tenants:
        raise ValueError("need at least one tenant")
    w = np.asarray([t.weight for t in tenants], np.float64)
    if (w <= 0).any():
        raise ValueError("tenant weights must be positive")
    return rng.choice(len(tenants), size=n, p=w / w.sum())


def churn_tenants(n_tenants: int, header_pages: int, page_size: int, *,
                  deadline_ms: Optional[float] = None,
                  ) -> Tuple[Tenant, ...]:
    """The eviction-churn adversary: ``n_tenants`` equal-weight tenants
    whose headers are each ``header_pages`` full pages. Size the pool so
    ``n_tenants * header_pages`` exceeds its cacheable capacity and the
    radix tree must evict one tenant's header to admit another's —
    steady-state thrash."""
    return tuple(
        Tenant(name=f"churn-{i}", weight=1.0,
               system_prompt_tokens=header_pages * page_size,
               deadline_ms=deadline_ms)
        for i in range(n_tenants))
