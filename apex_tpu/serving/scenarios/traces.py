"""Trace-driven load generation: seeded arrival processes + length mixes.

A *scenario* is only reproducible if its traffic is: every sampler here
is driven by an explicit ``numpy.random.Generator`` seeded from the
scenario's ``seed``, so materializing the same spec twice yields a
byte-identical trace (the determinism contract tests pin). A
materialized :class:`Trace` is a flat list of :class:`TraceEvent`\\ s —
arrival time, tenant, prompt tokens, output budget, SLO fields — that
the runner replays open-loop through :class:`ServingFrontend`; traces
round-trip through JSONL (``save``/``load``) so a workload can be
generated once, committed, and replayed forever.

Arrival processes (:class:`Arrival`):

- ``poisson`` — memoryless open-loop arrivals at ``rate_rps`` (the
  classic load-test baseline; exponential inter-arrival gaps).
- ``bursty`` — a two-state Markov-modulated Poisson process: the source
  alternates between a BURST state (``burst_rate_rps``, exponential
  holding time ``mean_burst_s``) and an IDLE state (``idle_rate_rps``,
  ``mean_idle_s``) — the on/off traffic that stresses queueing,
  deadlines, and preemption in a way a flat Poisson stream cannot.
- ``closed`` — ``users`` concurrent streams, each issuing its next
  request after an exponential think-time gap (``think_ms``). The trace
  materializes the think gaps as arrival offsets (zero-service-time
  approximation, so the trace stays a pure function of the seed); the
  replay is still open-loop over those times.

Length distributions (:class:`Lengths`): ``lognormal`` (the measured
shape of real prompt/output mixes), ``zipf`` (long tail — a few huge
requests among many small ones), ``uniform``, and ``fixed``; all clipped
to ``[lo, hi]``.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
from typing import List, Optional

import numpy as np

__all__ = ["Arrival", "Lengths", "TraceEvent", "Trace", "TRACE_SCHEMA"]

TRACE_SCHEMA = "apex-tpu/trace/v1"

_ARRIVAL_KINDS = ("poisson", "bursty", "closed")
_LENGTH_KINDS = ("lognormal", "zipf", "uniform", "fixed")


@dataclasses.dataclass(frozen=True)
class Arrival:
    """One arrival process (see module docstring for the kinds)."""

    kind: str = "poisson"
    rate_rps: float = 400.0          # poisson: mean arrival rate
    burst_rate_rps: float = 1600.0   # bursty: rate inside a burst
    idle_rate_rps: float = 50.0      # bursty: rate between bursts
    mean_burst_s: float = 0.02       # bursty: mean burst holding time
    mean_idle_s: float = 0.08        # bursty: mean idle holding time
    users: int = 4                   # closed: concurrent user streams
    think_ms: float = 10.0           # closed: mean think-time gap

    def sample_ms(self, n: int, rng: np.random.Generator) -> np.ndarray:
        """``n`` sorted arrival times in milliseconds from t=0."""
        if self.kind not in _ARRIVAL_KINDS:
            raise ValueError(f"unknown arrival kind {self.kind!r} "
                             f"(one of {_ARRIVAL_KINDS})")
        rates = {"poisson": ("rate_rps",),
                 "bursty": ("burst_rate_rps", "idle_rate_rps",
                            "mean_burst_s", "mean_idle_s"),
                 "closed": ("think_ms",)}[self.kind]
        for field in rates:
            if getattr(self, field) <= 0.0:
                raise ValueError(f"{self.kind} arrivals need "
                                 f"{field} > 0, got "
                                 f"{getattr(self, field)!r}")
        if self.kind == "closed" and self.users < 1:
            raise ValueError(f"closed arrivals need users >= 1, got "
                             f"{self.users!r}")
        if n < 1:
            return np.zeros((0,), np.float64)
        if self.kind == "poisson":
            gaps = rng.exponential(1.0 / self.rate_rps, n)
            return np.cumsum(gaps) * 1e3
        if self.kind == "closed":
            # each user: staggered start + exponential think gaps
            per_user = [[] for _ in range(self.users)]
            starts = rng.uniform(0.0, self.think_ms, self.users)
            for i in range(n):
                u = i % self.users
                prev = per_user[u][-1] if per_user[u] else starts[u]
                per_user[u].append(prev
                                   + rng.exponential(self.think_ms))
            return np.sort(np.concatenate(
                [np.asarray(x) for x in per_user if x]))[:n]
        # bursty: two-state MMPP — walk holding periods, fill each with
        # a Poisson stream at that state's rate until n arrivals land
        out: List[float] = []
        t, burst = 0.0, True
        while len(out) < n:
            hold = rng.exponential(
                self.mean_burst_s if burst else self.mean_idle_s)
            rate = self.burst_rate_rps if burst else self.idle_rate_rps
            at = t + rng.exponential(1.0 / rate)
            while at < t + hold and len(out) < n:
                out.append(at)
                at += rng.exponential(1.0 / rate)
            t += hold
            burst = not burst
        return np.asarray(out) * 1e3


@dataclasses.dataclass(frozen=True)
class Lengths:
    """One token-length distribution, clipped to ``[lo, hi]``."""

    kind: str = "lognormal"
    mean: float = 24.0               # lognormal/fixed: mean tokens
    sigma: float = 0.6               # lognormal: log-space sigma
    zipf_a: float = 1.5              # zipf: tail exponent (> 1)
    lo: int = 4
    hi: int = 64

    def sample(self, n: int, rng: np.random.Generator) -> np.ndarray:
        if self.kind not in _LENGTH_KINDS:
            raise ValueError(f"unknown length kind {self.kind!r} "
                             f"(one of {_LENGTH_KINDS})")
        if self.lo < 1 or self.hi < self.lo:
            raise ValueError(f"need 1 <= lo <= hi, got [{self.lo}, "
                             f"{self.hi}]")
        if self.kind == "fixed":
            vals = np.full((n,), self.mean)
        elif self.kind == "uniform":
            vals = rng.integers(self.lo, self.hi + 1, n)
        elif self.kind == "zipf":
            # long tail anchored at lo: most requests near lo, a few
            # reaching hi
            vals = self.lo + rng.zipf(self.zipf_a, n) - 1
        else:                        # lognormal with mean ~= self.mean
            mu = np.log(max(self.mean, 1.0)) - self.sigma ** 2 / 2.0
            vals = rng.lognormal(mu, self.sigma, n)
        return np.clip(np.asarray(vals).astype(np.int64),
                       self.lo, self.hi).astype(np.int32)


@dataclasses.dataclass
class TraceEvent:
    """One replayable request: everything ``ServingFrontend.submit``
    needs, in a JSON-stable form (token ids as plain ints)."""

    request_id: int
    arrival_ms: float
    tenant: str
    prompt: List[int]
    max_new_tokens: int
    priority: int = 0
    deadline_ms: Optional[float] = None
    tpot_slo_ms: Optional[float] = None

    def to_dict(self) -> dict:
        return {
            "request_id": self.request_id,
            "arrival_ms": round(float(self.arrival_ms), 6),
            "tenant": self.tenant,
            "prompt": [int(t) for t in self.prompt],
            "max_new_tokens": int(self.max_new_tokens),
            "priority": int(self.priority),
            "deadline_ms": self.deadline_ms,
            "tpot_slo_ms": self.tpot_slo_ms,
        }

    @classmethod
    def from_dict(cls, d: dict) -> "TraceEvent":
        return cls(request_id=d["request_id"],
                   arrival_ms=d["arrival_ms"], tenant=d["tenant"],
                   prompt=list(d["prompt"]),
                   max_new_tokens=d["max_new_tokens"],
                   priority=d.get("priority", 0),
                   deadline_ms=d.get("deadline_ms"),
                   tpot_slo_ms=d.get("tpot_slo_ms"))


@dataclasses.dataclass
class Trace:
    """A materialized workload: the scenario's events in arrival order,
    plus the provenance (scenario name + seed) that regenerates it."""

    scenario: str
    seed: int
    events: List[TraceEvent] = dataclasses.field(default_factory=list)

    def to_jsonl(self) -> str:
        """Canonical JSONL: a header line, then one compact sorted-key
        object per event — the byte representation the determinism
        contract (and :meth:`sha256`) is defined over."""
        lines = [json.dumps({"schema": TRACE_SCHEMA,
                             "scenario": self.scenario,
                             "seed": self.seed,
                             "n_events": len(self.events)},
                            sort_keys=True)]
        lines += [json.dumps(e.to_dict(), sort_keys=True,
                             separators=(",", ":"))
                  for e in self.events]
        return "\n".join(lines) + "\n"

    def sha256(self) -> str:
        return hashlib.sha256(self.to_jsonl().encode()).hexdigest()

    def save(self, path) -> None:
        with open(path, "w") as f:
            f.write(self.to_jsonl())

    @classmethod
    def load(cls, path) -> "Trace":
        with open(path) as f:
            lines = [ln for ln in f.read().splitlines() if ln.strip()]
        if not lines:
            raise ValueError(f"{path}: empty trace file")
        header = json.loads(lines[0])
        if header.get("schema") != TRACE_SCHEMA:
            raise ValueError(f"{path}: not a {TRACE_SCHEMA} trace "
                             f"(schema={header.get('schema')!r})")
        events = [TraceEvent.from_dict(json.loads(ln))
                  for ln in lines[1:]]
        if len(events) != header.get("n_events"):
            raise ValueError(
                f"{path}: truncated trace ({len(events)} events, header "
                f"says {header.get('n_events')})")
        return cls(scenario=header.get("scenario", "?"),
                   seed=header.get("seed", 0), events=events)
