"""Scenario specs + the open-loop replay runner.

A :class:`ScenarioSpec` is a declarative, JSON-round-trippable bundle of
(arrival process, length distributions, tenant set, engine knobs) plus a
seed; ``materialize(spec)`` turns it into a reproducible
:class:`~apex_tpu.serving.scenarios.traces.Trace` (pure function of the
spec — same seed, byte-identical trace) and ``run_scenario`` replays the
trace open-loop through a fresh :class:`ServingFrontend`, assembling the
pinned-schema report (``report.py``).

Replay semantics: requests are submitted when their trace arrival time
comes due on the host clock (scaled by ``time_scale``), with
``Request.arrival_time`` pinned to the INTENDED arrival — so queue-wait,
TTFT, and deadline accounting measure offered load, not how quickly the
replay loop happened to spin (the standard open-loop load-gen
convention: falling behind shows up as latency, not as a slower trace).
The pump is driven synchronously on the caller's thread, exactly the
``engine.run`` discipline, so replays are single-threaded and the greedy
outputs depend only on the trace (scheduling invariance — what lets the
determinism tests pin tokens across runs with different wall-clock
behavior).

``check=True`` turns a scenario into a correctness amplifier: every
replayed request's greedy output is re-derived by lock-step
``generate`` (token identity — the engine/cache/preemption machinery
re-derives nothing), and the whole trace is re-run as a fixed batch
through ``engine.run`` at a DIFFERENT ``sync_every`` (scheduling
invariance — outputs must not depend on arrival pacing or chunk size).
"""

from __future__ import annotations

import dataclasses
import json
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from apex_tpu.serving.scenarios import report as report_mod
from apex_tpu.serving.scenarios import tenants as tenants_mod
from apex_tpu.serving.scenarios.tenants import Tenant
from apex_tpu.serving.scenarios.traces import (Arrival, Lengths, Trace,
                                               TraceEvent)

__all__ = ["EngineSpec", "ScenarioSpec", "ScenarioResult", "MODELS",
           "model_config", "build_model", "materialize",
           "trace_requests", "replay", "run_scenario"]

#: scenario model registry: tiny CPU-fast configs (the scenario layer is
#: a workload/SLO harness, not a throughput bench — run_tpu_round's
#: on-chip numbers come from tpu_decode_bench.py at real sizes).
#: ``gpt2-small`` exists for the bench's full-size trace materialization
#: (vocab/position bounds); don't replay it on CPU.
MODELS = ("gpt2-tiny", "llama-tiny", "llama-tiny-windowed",
          "gpt2-small")

_MODEL_CACHE: Dict[str, tuple] = {}


def model_config(name: str):
    if name == "gpt2-tiny":
        from apex_tpu.models.gpt import gpt_tiny_config

        return gpt_tiny_config()
    if name == "gpt2-small":
        import jax.numpy as jnp

        from apex_tpu.models.gpt import gpt2_small_config

        return gpt2_small_config(dtype=jnp.bfloat16)
    if name == "llama-tiny":
        from apex_tpu.models.llama import llama_tiny_config

        return llama_tiny_config()
    if name == "llama-tiny-windowed":
        from apex_tpu.models.llama import llama_tiny_config

        # window < typical prompt+output so the band (and the engine's
        # page drops) actually engage
        return llama_tiny_config(sliding_window=16)
    raise ValueError(f"unknown scenario model {name!r} "
                     f"(one of {MODELS})")


def build_model(name: str):
    """``(config, model, variables)`` for a registry model —
    deterministic init (``PRNGKey(0)``), cached per process so repeated
    scenario runs share one weight set."""
    if name not in _MODEL_CACHE:
        import jax
        import jax.numpy as jnp

        cfg = model_config(name)
        if name.startswith("gpt2"):
            from apex_tpu.models.gpt import GPTModel

            model = GPTModel(cfg)
        else:
            from apex_tpu.models.llama import LlamaModel

            model = LlamaModel(cfg)
        v = model.init(jax.random.PRNGKey(0),
                       jnp.zeros((1, 8), jnp.int32))
        _MODEL_CACHE[name] = (cfg, model, v)
    return _MODEL_CACHE[name]


@dataclasses.dataclass(frozen=True)
class EngineSpec:
    """The engine/frontend half of a scenario: which model serves the
    trace and how the slots/pool/policy are configured.

    ``tensor_parallel > 1`` serves the trace through a
    :class:`~apex_tpu.serving.tp.TensorParallelPagedEngine` over a
    ``tp``-device mesh (docs/tp_serving.md) — the registry model's
    tp=1 weights are sharded on first use, so replays stay
    token-comparable to the single-chip engine and to lock-step
    ``generate`` (the ``check=True`` amplifiers bind exactly that).

    ``host_tier_bytes > 0`` gives the engine a host-RAM spill tier of
    that byte budget under the device pool (docs/serving.md "Tiered KV
    pool"): evicted/spilled refcount-0 pages demote instead of
    dropping, and churned hits promote instead of re-prefilling. The
    report then carries a ``host_tier`` block with the tier-on vs
    tier-off hit-rate A/B (the same trace re-replayed tier-off).

    ``replicas > 1`` serves the trace through a
    :class:`~apex_tpu.serving.router.ReplicaRouter` over that many
    frontend+engine replicas (docs/router.md): ``routing`` picks the
    router policy (``"affinity"`` keys on the trace event's TENANT —
    the system-prompt unit — so one tenant's requests land where its
    header pages are cached; ``"round_robin"`` is the A/B baseline),
    and ``compare_round_robin=True`` re-replays the same trace through
    a fresh round-robin router so the report's ``router`` block can
    bank both hit rates and their delta. ``ScenarioSpec.faults``
    injects deterministic chaos into the replicas
    (``serving/faults.py``).

    ``http=True`` replays the trace OVER THE WIRE: real
    ``POST /v1/generate`` SSE streams against a localhost
    :class:`~apex_tpu.serving.http.HttpServingServer`
    (``scenarios/http_driver.py``), one client thread per request —
    the outputs checked are what the clients read off their sockets,
    and the NETWORK fault kinds (``client_disconnect``,
    ``slow_reader``, ``conn_reset``) are delivered on the client side.
    ``backpressure_window`` bounds unconsumed in-flight tokens per
    stream (``ServingFrontend``'s spill-through-preemption window) and
    ``sse_pad_bytes`` pads every SSE frame so socket backpressure
    reaches that window quickly on tiny scenarios."""

    model: str = "gpt2-tiny"
    num_slots: int = 3
    page_size: int = 8
    sync_every: int = 1
    prefix_cache: bool = True
    num_pages: Optional[int] = None      # None = worst-case pool
    host_tier_bytes: int = 0             # >0 = host-RAM spill tier budget
    preempt_on_priority: bool = False
    preempt_margin_ms: float = 50.0
    tensor_parallel: int = 1             # >1 = TP mesh engine
    replicas: int = 1                    # >1 = ReplicaRouter DP serving
    routing: str = "affinity"            # router policy (replicas > 1)
    compare_round_robin: bool = False    # bank the affinity-vs-RR A/B
    http: bool = False                   # replay over localhost HTTP/SSE
    backpressure_window: Optional[int] = None  # frontend spill window
    sse_pad_bytes: int = 0               # pad SSE frames (chaos knob)
    sndbuf: Optional[int] = None         # shrink kernel send buffer
    #                                      (socket backpressure reaches
    #                                      the window fast; chaos knob)


@dataclasses.dataclass(frozen=True)
class ScenarioSpec:
    """One declarative scenario. ``materialize`` consumes everything but
    ``engine``/``time_scale``; ``replay`` consumes those."""

    name: str
    seed: int = 0
    n_requests: int = 24
    arrival: Arrival = Arrival()
    prompt_lens: Lengths = Lengths()
    output_lens: Lengths = Lengths(kind="uniform", lo=4, hi=12)
    tenants: Tuple[Tenant, ...] = (Tenant("default"),)
    engine: EngineSpec = EngineSpec()
    time_scale: float = 1.0              # arrival-time multiplier at replay
    description: str = ""
    #: deterministic chaos plan (``serving/faults.py``) delivered into
    #: the replica frontends at replay — only meaningful with
    #: ``engine.replicas > 1`` (a single frontend has no survivor)
    faults: Tuple = ()

    def to_json(self) -> str:
        return json.dumps(dataclasses.asdict(self), sort_keys=True,
                          indent=2)

    @classmethod
    def from_json(cls, text: str) -> "ScenarioSpec":
        from apex_tpu.serving.faults import FaultSpec

        d = json.loads(text)
        d["arrival"] = Arrival(**d.get("arrival", {}))
        d["prompt_lens"] = Lengths(**d.get("prompt_lens", {}))
        d["output_lens"] = Lengths(**d.get("output_lens", {}))
        d["tenants"] = tuple(Tenant(**t) for t in d.get("tenants", ()))
        d["engine"] = EngineSpec(**d.get("engine", {}))
        d["faults"] = tuple(FaultSpec(**f) for f in d.get("faults", ()))
        return cls(**d)


@dataclasses.dataclass
class ScenarioResult:
    """One run's artifacts: the pinned-schema ``report`` (serialized),
    plus the in-memory trace/outputs the tests pin determinism over."""

    spec: ScenarioSpec
    trace: Trace
    outputs: List[np.ndarray]
    stats: dict
    report: dict
    #: the router's kill-triggered postmortem bundle (replicated chaos
    #: scenarios where a replica died; None otherwise) — schema-pinned,
    #: ``apex_tpu.obs.fleet.validate_flight``-clean
    flight: Optional[dict] = None


def materialize(spec: ScenarioSpec) -> Trace:
    """Sample the spec into a trace — a pure function of the spec (the
    PRNG is ``default_rng(spec.seed)`` and nothing else): arrivals,
    tenant assignment, tenant-header + random-tail prompts, output
    budgets, all clipped to the model's position table."""
    cfg = model_config(spec.engine.model)
    max_pos = cfg.max_position_embeddings
    rng = np.random.default_rng(spec.seed)
    n = spec.n_requests
    arrivals = spec.arrival.sample_ms(n, rng)
    tails = spec.prompt_lens.sample(n, rng)
    outs = spec.output_lens.sample(n, rng)
    t_idx = tenants_mod.assign_tenants(spec.tenants, n, rng)
    headers = [tenants_mod.system_prompt(t, cfg.vocab_size, spec.seed)
               for t in spec.tenants]
    events: List[TraceEvent] = []
    for name, header in zip((t.name for t in spec.tenants), headers):
        if header.shape[0] > max_pos - 2:
            raise ValueError(
                f"scenario {spec.name!r}: tenant {name!r}'s system "
                f"prompt ({header.shape[0]} tokens) leaves no room in "
                f"{spec.engine.model!r}'s position table ({max_pos}) "
                f"for the >=1 tail + >=1 generated token every request "
                f"needs")
    for i in range(n):
        ten = spec.tenants[int(t_idx[i])]
        header = headers[int(t_idx[i])]
        # clip to the position table: header + >=1 tail token + >=1
        # generated token must all fit (header length validated above)
        tail_len = int(np.clip(tails[i], 1,
                               max_pos - 1 - header.shape[0]))
        tail = rng.integers(0, cfg.vocab_size, tail_len)
        prompt = np.concatenate([header, tail.astype(np.int32)])
        # a tenant with a pinned output budget overrides the sampled one
        want_out = ten.output_tokens if ten.output_tokens is not None \
            else outs[i]
        max_new = int(np.clip(want_out, 1, max_pos - prompt.shape[0]))
        events.append(TraceEvent(
            request_id=i, arrival_ms=float(arrivals[i]),
            tenant=ten.name, prompt=[int(t) for t in prompt],
            max_new_tokens=max_new, priority=ten.priority,
            deadline_ms=ten.deadline_ms, tpot_slo_ms=ten.tpot_slo_ms))
    return Trace(scenario=spec.name, seed=spec.seed, events=events)


def _event_request(e: TraceEvent, *, arrival_time=None):
    """The single TraceEvent -> Request mapping (every consumer builds
    through here, so a new trace-carried field cannot silently reach
    only one of the replay / fixed-batch paths)."""
    from apex_tpu.serving.scheduler import Request

    return Request(prompt=np.asarray(e.prompt, np.int32),
                   max_new_tokens=e.max_new_tokens,
                   priority=e.priority, deadline_ms=e.deadline_ms,
                   arrival_time=arrival_time, tpot_slo_ms=e.tpot_slo_ms)


def trace_requests(trace: Trace) -> List:
    """The trace's events as engine ``Request`` objects (arrival times
    are the REPLAY loop's business — a fixed-list ``engine.run`` over
    these ignores pacing, which is exactly what the bench's closed-loop
    throughput sections want)."""
    return [_event_request(e) for e in trace.events]


_TP_MODEL_CACHE: Dict[tuple, tuple] = {}


def _build_tp_model(name: str, tp: int):
    """``(config, model, sharded_variables, mesh)`` for a registry model
    at tensor-parallel degree ``tp`` — the tp=1 cached weights sliced
    over a fresh ``tp``-device mesh, cached per (name, tp) like
    ``build_model``."""
    if (name, tp) not in _TP_MODEL_CACHE:
        import dataclasses as _dc

        from apex_tpu.serving.tp import shard_model_variables, tp_mesh

        cfg, model, v = build_model(name)
        cfg_tp = _dc.replace(cfg, tensor_parallel_size=tp)
        model_tp = type(model)(cfg_tp)
        mesh = tp_mesh(tp)
        v_tp, _ = shard_model_variables(model_tp, v, mesh)
        _TP_MODEL_CACHE[(name, tp)] = (cfg_tp, model_tp, v_tp, mesh)
    return _TP_MODEL_CACHE[(name, tp)]


def _build_engine(spec: ScenarioSpec, model, variables, *,
                  sync_every: Optional[int] = None):
    from apex_tpu.serving.scheduler import PagedDecodeEngine

    es = spec.engine
    kw = dict(num_slots=es.num_slots, page_size=es.page_size,
              num_pages=es.num_pages,
              sync_every=sync_every if sync_every is not None
              else es.sync_every,
              prefix_cache=es.prefix_cache,
              host_tier_bytes=es.host_tier_bytes or None)
    if es.tensor_parallel > 1:
        from apex_tpu.serving.tp import TensorParallelPagedEngine

        _, model_tp, v_tp, mesh = _build_tp_model(es.model,
                                                  es.tensor_parallel)
        return TensorParallelPagedEngine(model_tp, v_tp, mesh=mesh, **kw)
    return PagedDecodeEngine(model, variables, **kw)


def _build_router(spec: ScenarioSpec, model, variables, *,
                  routing: Optional[str] = None, faults=None):
    """N fresh frontend+engine replicas behind one
    :class:`~apex_tpu.serving.router.ReplicaRouter`, with the spec's
    fault plan (or an override) injected through the frontends' fault
    hooks."""
    from apex_tpu.serving.faults import FaultPlan
    from apex_tpu.serving.frontend import ServingFrontend
    from apex_tpu.serving.policy import PriorityDeadlinePolicy
    from apex_tpu.serving.router import ReplicaRouter, RouterPolicy

    es = spec.engine
    plan = FaultPlan(specs=tuple(spec.faults if faults is None
                                 else faults))
    frontends = []
    for i in range(es.replicas):
        engine = _build_engine(spec, model, variables)
        policy = PriorityDeadlinePolicy(
            preempt_on_priority=es.preempt_on_priority,
            preempt_margin_ms=es.preempt_margin_ms)
        frontends.append(ServingFrontend(engine, policy=policy,
                                         fault_hook=plan.injector(i)))
    return ReplicaRouter(
        frontends,
        policy=RouterPolicy(routing=routing if routing is not None
                            else es.routing,
                            backoff_base_ms=2.0))


def _replay_router(spec: ScenarioSpec, trace: Trace, router):
    """Open-loop replay through a :class:`ReplicaRouter` (the
    ``engine.replicas > 1`` path): affinity keys on the trace event's
    TENANT (the system-prompt unit), the router's synchronous ``pump``
    drives every replica. Raises if any request failed terminally —
    catalog chaos scenarios are sized to always recover; non-recovery
    coverage lives in tests/test_router.py."""
    events = trace.events
    scale = spec.time_scale
    handles = {}
    t0 = time.perf_counter()
    i = 0
    while i < len(events):
        now_s = time.perf_counter() - t0
        while (i < len(events)
               and events[i].arrival_ms * scale * 1e-3 <= now_s):
            e = events[i]
            req = _event_request(
                e, arrival_time=t0 + e.arrival_ms * scale * 1e-3)
            handles[e.request_id] = router.submit(
                req, request_id=e.request_id, affinity_key=e.tenant)
            i += 1
        if not router.pump() and i < len(events):
            gap = (events[i].arrival_ms * scale * 1e-3
                   - (time.perf_counter() - t0))
            time.sleep(min(max(gap, 0.0), 0.002))
    router.drain()
    wall_s = time.perf_counter() - t0
    outputs = [np.asarray(handles[e.request_id].result(timeout=0),
                          np.int32) for e in events]
    return outputs, wall_s


def replay(spec: ScenarioSpec, trace: Trace, *, engine=None):
    """Open-loop replay of ``trace`` through a fresh frontend; returns
    ``(outputs, stats, tracer, wall_s)``. ``engine=`` injects a
    pre-built (e.g. pre-warmed) engine. With ``engine.replicas > 1``
    the trace replays through a fresh :class:`ReplicaRouter` instead —
    ``stats`` is then the router's stats dict (aggregated engine
    counters included) and ``tracer`` the router's cross-replica
    lifecycle adapter."""
    from apex_tpu.serving.frontend import ServingFrontend
    from apex_tpu.serving.policy import PriorityDeadlinePolicy

    if spec.engine.http and engine is None:
        from apex_tpu.serving.scenarios.http_driver import replay_http

        outputs, stats, tracer, wall_s, http_block = replay_http(
            spec, trace)
        stats = dict(stats)
        stats["http"] = http_block       # run_scenario lifts this out
        return outputs, stats, tracer, wall_s
    if spec.engine.replicas > 1 and engine is None:
        _, model, v = build_model(spec.engine.model)
        router = _build_router(spec, model, v)
        outputs, wall_s = _replay_router(spec, trace, router)
        # one final federation pass so the banked fleet block reflects
        # end-of-run state; the kill-triggered flight (if any replica
        # died) rides along for run_scenario to lift out
        router.fleet.tick(force=True)
        stats = router.stats()
        stats["flight"] = router.last_flight
        return outputs, stats, router, wall_s
    if engine is None:
        _, model, v = build_model(spec.engine.model)
        engine = _build_engine(spec, model, v)
    policy = PriorityDeadlinePolicy(
        preempt_on_priority=spec.engine.preempt_on_priority,
        preempt_margin_ms=spec.engine.preempt_margin_ms)
    frontend = ServingFrontend(engine, policy=policy)
    events = trace.events
    scale = spec.time_scale
    handles = {}
    t0 = time.perf_counter()
    i = 0
    while i < len(events):
        now_s = time.perf_counter() - t0
        while (i < len(events)
               and events[i].arrival_ms * scale * 1e-3 <= now_s):
            e = events[i]
            req = _event_request(
                e, arrival_time=t0 + e.arrival_ms * scale * 1e-3)
            handles[e.request_id] = frontend.submit(
                req, request_id=e.request_id)
            i += 1
        if not frontend.pump() and i < len(events):
            # idle before the next arrival: nap up to it (bounded so the
            # loop stays responsive to device completions)
            gap = (events[i].arrival_ms * scale * 1e-3
                   - (time.perf_counter() - t0))
            time.sleep(min(max(gap, 0.0), 0.002))
    frontend.drain()
    wall_s = time.perf_counter() - t0
    outputs = [np.asarray(handles[e.request_id].result(timeout=0),
                          np.int32) for e in events]
    return outputs, frontend.stats(), frontend.tracer, wall_s


def _net_prefix_ids(spec: ScenarioSpec) -> set:
    """Request ids whose replayed output is a PREFIX by design: a
    ``client_disconnect`` drops the stream after ``at`` tokens, so the
    client banked only what it read before dropping (the server then
    cancels at the next sync boundary — the amplifiers must tolerate
    the truncation but still bind every delivered token)."""
    ids: set = set()
    for f in spec.faults:
        if getattr(f, "kind", None) == "client_disconnect":
            ids.update(range(f.count))
    return ids


def _check_greedy_identity(spec: ScenarioSpec, trace: Trace,
                           outputs: List[np.ndarray],
                           limit: int = 16) -> int:
    """Token identity vs lock-step ``generate`` for up to ``limit``
    replayed requests (tiny models — each re-derivation is one eager
    prefill + scan). Raises AssertionError on the first mismatch.
    Disconnect-faulted ids (``_net_prefix_ids``) compare as prefixes —
    every token the client read must still be the lock-step token."""
    from apex_tpu.models.generation import generate

    prefix_ok = _net_prefix_ids(spec)
    _, model, v = build_model(spec.engine.model)
    n = min(len(trace.events), limit)
    for e, out in list(zip(trace.events, outputs))[:n]:
        prompt = np.asarray(e.prompt, np.int32)
        ref = np.asarray(generate(model, v, prompt[None],
                                  max_new_tokens=e.max_new_tokens))
        ref_gen = ref[0, prompt.shape[0]:]
        got = np.asarray(out)
        if e.request_id in prefix_ok:
            ref_gen = ref_gen[:got.shape[0]]
        if not np.array_equal(got, ref_gen):
            raise AssertionError(
                f"scenario {spec.name!r} request {e.request_id}: "
                f"replayed greedy output diverges from lock-step "
                f"generate ({got[:8]}... vs "
                f"{ref_gen[:8]}...)")
    return n


def _check_scheduling_invariance(spec: ScenarioSpec, trace: Trace,
                                 outputs: List[np.ndarray]) -> None:
    """Re-run the SAME trace as a fixed batch through ``engine.run`` at
    a different ``sync_every`` — greedy outputs must not depend on
    arrival pacing, admission order, or chunk size.
    Disconnect-faulted ids compare as prefixes (the fixed batch runs
    them to completion; the replay banked what the client read)."""
    prefix_ok = _net_prefix_ids(spec)
    _, model, v = build_model(spec.engine.model)
    alt_sync = spec.engine.sync_every % 3 + 1     # always != sync_every
    engine = _build_engine(spec, model, v, sync_every=alt_sync)
    outs2, _ = engine.run(trace_requests(trace))
    for e, a, b in zip(trace.events, outputs, outs2):
        a, b = np.asarray(a), np.asarray(b)
        if e.request_id in prefix_ok:
            b = b[:a.shape[0]]
        if not np.array_equal(a, b):
            raise AssertionError(
                f"scenario {spec.name!r} request {e.request_id}: "
                f"greedy output changed under a different schedule "
                f"(sync_every {spec.engine.sync_every} -> {alt_sync})")


def _router_block(spec: ScenarioSpec, trace: Trace,
                  stats: dict) -> dict:
    """The report's ``router`` block for a replicated scenario:
    supervision/failover facts plus — with ``compare_round_robin`` —
    the affinity-vs-round-robin hit-rate A/B (the same trace re-played
    through a fresh round-robin router, faults stripped so the baseline
    measures routing, not luck-of-the-kill)."""
    block = {
        "replicas": int(stats.get("replicas", 0)),
        "replicas_alive": int(stats.get("replicas_alive", 0)),
        "routing": spec.engine.routing,
        "failovers": int(stats.get("failovers", 0)),
        "failover_requests": int(stats.get("failover_requests", 0)),
        "failover_recovered": int(stats.get("failover_recovered", 0)),
        "failover_recovered_rate":
            round(float(stats.get("failover_recovered_rate", 1.0)), 4),
        "shed_requests": int(stats.get("shed_requests", 0)),
        "migrations": int(stats.get("migrations", 0)),
        "replica_deaths": int(stats.get("replica_deaths", 0)),
        "affinity_hit_rate":
            round(float(stats.get("prefix_hit_rate", 0.0)), 4),
    }
    if spec.engine.compare_round_robin:
        _, model, v = build_model(spec.engine.model)
        rr_router = _build_router(spec, model, v,
                                  routing="round_robin", faults=())
        _replay_router(spec, trace, rr_router)
        rr_stats = rr_router.stats()
        rr_rate = round(float(rr_stats.get("prefix_hit_rate", 0.0)), 4)
        block["round_robin_hit_rate"] = rr_rate
        block["affinity_delta_hit_rate"] = round(
            block["affinity_hit_rate"] - rr_rate, 4)
    return block


def _host_tier_block(spec: ScenarioSpec, trace: Trace,
                     stats: dict) -> dict:
    """The report's ``host_tier`` block for a tiered scenario
    (``engine.host_tier_bytes > 0``): the tier's demote/promote facts
    plus the tier-on vs tier-off hit-rate A/B — the same trace
    re-replayed through a fresh engine with the tier OFF, so the banked
    delta measures what demote/promote earned, not workload luck. The
    acceptance bar (docs/scenarios.md): at a thrash-sized pool the
    delta must be strictly positive."""
    tier_on_rate = round(float(stats.get("prefix_hit_rate", 0.0)), 4)
    off_spec = dataclasses.replace(
        spec, engine=dataclasses.replace(spec.engine, host_tier_bytes=0))
    _, off_stats, _, _ = replay(off_spec, trace)
    tier_off_rate = round(float(off_stats.get("prefix_hit_rate", 0.0)), 4)
    return {
        "budget_bytes": int(spec.engine.host_tier_bytes),
        "demotes": int(stats.get("host_tier_demotes", 0)),
        "promotes": int(stats.get("host_tier_promotes", 0)),
        "host_evicted_pages": int(stats.get("host_tier_evicted_pages",
                                            0)),
        "promote_hit_rate":
            round(float(stats.get("host_tier_promote_hit_rate", 0.0)), 4),
        "tier_on_hit_rate": tier_on_rate,
        "tier_off_hit_rate": tier_off_rate,
        "tier_delta_hit_rate": round(tier_on_rate - tier_off_rate, 4),
    }


def run_scenario(spec: ScenarioSpec, *, check: bool = False,
                 trace: Optional[Trace] = None) -> ScenarioResult:
    """Materialize (unless a saved ``trace`` is injected), replay, and
    report one scenario. ``check=True`` additionally runs the
    token-identity and scheduling-invariance amplifiers and records
    their outcome under ``report["checks"]`` (raising on divergence).
    Replicated scenarios (``engine.replicas > 1``) add the ``router``
    block — failover/recovery facts and, with
    ``compare_round_robin``, the affinity-vs-round-robin hit-rate A/B.
    Tiered scenarios (``engine.host_tier_bytes > 0``) add the
    ``host_tier`` block — demote/promote facts and the tier-on vs
    tier-off hit-rate A/B on the same trace."""
    if trace is None:
        trace = materialize(spec)
    outputs, stats, tracer, wall_s = replay(spec, trace)
    http_block = stats.pop("http", None) if isinstance(stats, dict) \
        else None
    fleet_block = stats.pop("fleet", None) if isinstance(stats, dict) \
        else None
    flight = stats.pop("flight", None) if isinstance(stats, dict) \
        else None
    checks = None
    if check:
        n_checked = _check_greedy_identity(spec, trace, outputs)
        _check_scheduling_invariance(spec, trace, outputs)
        checks = {"greedy_identity_requests": n_checked,
                  "scheduling_invariance": True}
    router_block = _router_block(spec, trace, stats) \
        if spec.engine.replicas > 1 else None
    host_tier_block = _host_tier_block(spec, trace, stats) \
        if spec.engine.host_tier_bytes > 0 else None
    rep = report_mod.build_report(spec, trace, outputs, stats, tracer,
                                  wall_s, checks=checks,
                                  router=router_block, http=http_block,
                                  host_tier=host_tier_block,
                                  fleet=fleet_block)
    report_mod.validate_report(rep)
    return ScenarioResult(spec=spec, trace=trace, outputs=outputs,
                          stats=stats, report=rep, flight=flight)
