"""Scenario engine: trace-driven load generation + multi-tenant
prefix workloads over the serving stack (ROADMAP open item 5).

The bench layer used to hard-code two synthetic workloads; this package
makes workloads DECLARATIVE, SEEDED, and REPLAYABLE:

- :mod:`traces` — composable arrival processes (Poisson, bursty
  Markov-modulated on/off, closed-loop) and length distributions
  (lognormal / Zipf / uniform / fixed), all driven by one explicit seed;
  materialized traces round-trip through JSONL.
- :mod:`tenants` — N tenants with distinct system prompts and
  priority/deadline/TPOT-SLO profiles contending for one radix prefix
  cache, plus the adversarial eviction-churn tenant set.
- :mod:`runner` / :mod:`report` — open-loop replay through
  :class:`~apex_tpu.serving.frontend.ServingFrontend` into a
  pinned-schema per-tenant + aggregate SLO report; ``check=`` turns any
  scenario into a correctness amplifier (greedy token identity vs
  lock-step, scheduling invariance across chunk sizes).
- :mod:`library` — the named catalog (``steady-poisson``,
  ``burst-storm``, ``long-tail-lengths``,
  ``multi-tenant-shared-prefix``, ``eviction-churn``,
  ``priority-flood``, ``windowed-llama``, the two bench workloads, the
  ``preemption-storm`` adversary, and the replicated-serving tier:
  ``chaos-replica-kill`` / ``chaos-pump-stall`` (seeded fault injection
  through ``serving/faults.py``), ``router-affinity-ab`` (the
  affinity-vs-round-robin hit-rate A/B over ``serving/router.py``), and
  the over-the-wire network-chaos tier ``chaos-slow-reader`` /
  ``chaos-disconnect-storm`` (``EngineSpec(http=True)`` replays the
  trace through a real localhost HTTP/SSE server via
  :mod:`http_driver`, delivering the NETWORK fault kinds on the client
  side of the socket; the report grows an ``http`` block)).

CLI: ``python -m apex_tpu.serving.scenarios --list`` /
``--scenario NAME [--scenario NAME ...] --json OUT --seed N [--check]``
(also installed as ``apex-tpu-scenarios``). ``run_tpu_round.sh`` runs a
two-scenario smoke per round, banking ``SCENARIOS_<tag>.json`` whose
``scenario.<name>.*`` SLO fields the perf ledger band-gates.

Docs: docs/scenarios.md (spec format, seeding contract, catalog, report
schema, extension guide).
"""

from apex_tpu.serving.scenarios.library import (  # noqa: F401
    SCENARIOS,
    scenario_names,
    scenario_spec,
)
from apex_tpu.serving.scenarios.report import (  # noqa: F401
    AGGREGATE_FIELDS,
    HOST_TIER_FIELDS,
    HTTP_FIELDS,
    REPORT_SCHEMA,
    ROUTER_FIELDS,
    SCENARIOS_SCHEMA,
    TENANT_FIELDS,
    validate_report,
)
from apex_tpu.serving.scenarios.runner import (  # noqa: F401
    EngineSpec,
    ScenarioResult,
    ScenarioSpec,
    build_model,
    materialize,
    replay,
    run_scenario,
    trace_requests,
)
from apex_tpu.serving.scenarios.tenants import Tenant  # noqa: F401
from apex_tpu.serving.scenarios.traces import (  # noqa: F401
    Arrival,
    Lengths,
    Trace,
    TraceEvent,
)
