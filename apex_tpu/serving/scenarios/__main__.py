"""CLI for the scenario engine (``python -m apex_tpu.serving.scenarios``,
installed as ``apex-tpu-scenarios``).

Runs catalog scenarios on the local backend (CI pins CPU via
``JAX_PLATFORMS=cpu``) and writes one JSON document —
``{"schema": "apex-tpu/scenarios/v1", "scenarios": {name: report}}`` —
whose per-scenario reports the perf ledger's ``--bench`` extraction
understands (``scenario.<name>.ttft_ms_p95`` etc.). Exit codes: 0 ok,
1 a ``--check`` amplifier found divergence, 2 usage/unknown scenario.
"""

from __future__ import annotations

import json
import os
import time
from typing import List, Optional

__all__ = ["main"]


def main(argv: Optional[List[str]] = None) -> int:
    import argparse

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    parser = argparse.ArgumentParser(
        prog="python -m apex_tpu.serving.scenarios",
        description="Replay named serving scenarios and report "
                    "per-tenant SLO percentiles (docs/scenarios.md)")
    parser.add_argument("--list", action="store_true",
                        help="list the scenario catalog and exit")
    parser.add_argument("--scenario", action="append", default=[],
                        metavar="NAME",
                        help="scenario to run (repeatable)")
    parser.add_argument("--seed", type=int, default=0,
                        help="trace seed (same seed = identical trace "
                             "+ greedy tokens)")
    parser.add_argument("--json", default=None, metavar="OUT",
                        help="write the scenarios document here")
    parser.add_argument("--check", action="store_true",
                        help="run the correctness amplifiers (greedy "
                             "token identity vs lock-step + scheduling "
                             "invariance)")
    parser.add_argument("--http", action="store_true",
                        help="force replay over localhost HTTP/SSE "
                             "(EngineSpec(http=True)): every request is "
                             "a real POST /v1/generate stream and the "
                             "report grows the pinned http block")
    parser.add_argument("--fleet", default=None, metavar="OUT",
                        help="write the replicated scenarios' federated "
                             "fleet blocks here (one JSON document, "
                             "docs/observability.md \"Fleet plane\")")
    parser.add_argument("--flight", default=None, metavar="OUT",
                        help="write the kill-triggered postmortem "
                             "flight bundle here (schema-validated; "
                             "skipped when no replica died)")
    parser.add_argument("--save-trace", default=None, metavar="DIR",
                        help="save each materialized trace as "
                             "<DIR>/<name>.trace.jsonl")
    parser.add_argument("--trace", default=None, metavar="JSONL",
                        help="replay a saved trace instead of "
                             "materializing (single --scenario only)")
    args = parser.parse_args(argv)

    from apex_tpu.serving.scenarios import library, report, runner
    from apex_tpu.serving.scenarios.traces import Trace

    if args.list:
        for name in library.scenario_names():
            spec = library.scenario_spec(name)
            print(f"{name:28s} n={spec.n_requests:<3d} "
                  f"model={spec.engine.model:<20s} "
                  f"{spec.description}")
        return 0
    if not args.scenario:
        parser.error("--scenario NAME required (or --list)")
    if args.trace and len(args.scenario) != 1:
        parser.error("--trace replays exactly one --scenario")

    # resolve every name BEFORE replaying anything: a typo in the third
    # --scenario must not discard the first two scenarios' minutes of
    # replay (the same evidence-preservation rule as check_failed below)
    specs = {}
    for name in args.scenario:
        try:
            specs[name] = library.scenario_spec(name, seed=args.seed)
        except KeyError as e:
            print(f"[scenarios] {e.args[0]}")
            return 2
    if args.http:
        # drive the same catalog entries over the wire: replay_http boots
        # an HttpServingServer and each trace event becomes a real SSE
        # stream (docs/http.md) — the amplifiers then prove the transport
        # corrupts nothing
        import dataclasses
        specs = {name: dataclasses.replace(
                     spec,
                     engine=dataclasses.replace(spec.engine, http=True))
                 for name, spec in specs.items()}

    reports = {}
    fleets = {}
    flight_doc = None
    check_failed = False
    doc_seed = args.seed
    for name in args.scenario:
        spec = specs[name]
        trace = None
        if args.trace:
            try:
                trace = Trace.load(args.trace)
            except (OSError, ValueError) as e:
                print(f"[scenarios] cannot load trace: {e}")
                return 2
            if trace.scenario != name:
                # a trace is only replayable under the spec that
                # materialized it — the events carry the spec's model
                # bounds (vocab/position table), and the report would
                # otherwise bank A's trace under B's ledger baselines
                print(f"[scenarios] trace {args.trace} was materialized "
                      f"for scenario {trace.scenario!r}, not {name!r}")
                return 2
            if trace.seed != args.seed:
                # the report's seed field must name the seed that
                # regenerates the trace (the documented seed ->
                # trace_sha256 contract), not whatever --seed defaulted
                # to on the replay invocation
                spec = library.scenario_spec(name, seed=trace.seed)
                doc_seed = trace.seed
        t0 = time.perf_counter()
        try:
            result = runner.run_scenario(spec, check=args.check,
                                         trace=trace)
        except AssertionError as e:
            print(f"[scenarios] CHECK FAILED: {e}")
            check_failed = True
            continue
        agg = result.report["aggregate"]
        print(f"[scenarios] {name}: {result.report['n_requests']} req "
              f"/ {result.report['n_tenants']} tenant(s) in "
              f"{time.perf_counter() - t0:.1f}s — "
              f"ttft_p95={agg['ttft_ms_p95']:.1f}ms "
              f"tpot_p95={agg['tpot_ms_p95']:.2f}ms "
              f"miss_rate={agg['deadline_miss_rate']:.2f} "
              f"hit_rate={agg['prefix_hit_rate']:.2f}", flush=True)
        reports[name] = result.report
        if "fleet" in result.report:
            fleets[name] = result.report["fleet"]
        if result.flight is not None and flight_doc is None:
            from apex_tpu.obs.fleet import validate_flight

            flight_doc = validate_flight(dict(result.flight,
                                              tag=name))
        if args.save_trace:
            os.makedirs(args.save_trace, exist_ok=True)
            path = os.path.join(args.save_trace,
                                f"{name}.trace.jsonl")
            result.trace.save(path)
            print(f"[scenarios] trace saved to {path}")

    # a --check divergence exits 1, but only after every requested
    # scenario has run and the completed reports are on disk — the
    # failing amplifier's evidence (and the passing scenarios' ~minutes
    # of replay) must not be discarded
    doc = {"schema": report.SCENARIOS_SCHEMA, "seed": doc_seed,
           "time_unix": round(time.time(), 3), "scenarios": reports}
    if args.json:
        with open(args.json, "w") as f:
            json.dump(doc, f, indent=2, sort_keys=True)
        print(f"[scenarios] report written to {args.json}")
    if args.fleet:
        fleet_out = {"schema": report.FLEET_DOC_SCHEMA, "seed": doc_seed,
                     "time_unix": round(time.time(), 3),
                     "scenarios": fleets}
        with open(args.fleet, "w") as f:
            json.dump(fleet_out, f, indent=2, sort_keys=True)
        print(f"[scenarios] fleet blocks written to {args.fleet}")
    if args.flight:
        if flight_doc is None:
            print("[scenarios] no flight recorded (no replica died); "
                  f"skipping {args.flight}")
        else:
            with open(args.flight, "w") as f:
                json.dump(flight_doc, f, indent=2, sort_keys=True)
            print(f"[scenarios] flight bundle written to {args.flight}")
    return 1 if check_failed else 0


if __name__ == "__main__":
    raise SystemExit(main())
