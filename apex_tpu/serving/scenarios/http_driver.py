"""Closed-loop HTTP client driver for scenario replay (ISSUE 15).

``EngineSpec(http=True)`` routes a scenario's replay over the wire: the
trace's requests are submitted as real ``POST /v1/generate`` streams
against an in-process :class:`~apex_tpu.serving.http.HttpServingServer`
on localhost, one client thread per request honoring the trace's
arrival times. Outputs are what the CLIENT read off the socket — so the
greedy-identity amplifier proves the whole transport (submit body, SSE
framing, ack-driven backpressure, cancel-on-disconnect) end to end, not
just the in-process pump.

The driver is also the delivery vehicle for the NETWORK fault kinds
(``serving/faults.py``): they model the wire, so they are applied on
the client side of the socket, never through the frontend's
``fault_hook`` seams —

- ``client_disconnect`` — read ``at`` token events, then drop the
  connection for real (``sock.shutdown(SHUT_RDWR)`` — ``close()``
  alone defers the FIN while a ``makefile`` reader holds the fd, and
  the server would never see the drop); the request's banked output is
  the prefix the client read, and the server must cancel + free pages.
- ``slow_reader`` — read ``at`` tokens, stop reading for ``delay_ms``
  with the socket open (recv window fills, ``writer.drain()`` parks,
  unconsumed tokens cross the frontend's ``backpressure_window``, the
  slot spills), then resume to completion — token-identically, which
  the identity amplifier then proves.
- ``conn_reset`` — tear the connection mid-REQUEST (half the bytes,
  then an RST via ``SO_LINGER 0``), then retry once on a fresh
  connection: the request never reached the engine, the server must
  survive the torn submit, and the retry completes normally.

Faults target request ids ``{0, …, count-1}`` (``FaultPlan.
net_faults_for``), so the checks know exactly which outputs are
prefixes.
"""

from __future__ import annotations

import json
import socket
import struct
import threading
import time
from typing import Dict, List, Optional

import numpy as np

__all__ = ["replay_http"]


def _post_stream(host: str, port: int, body: dict, *,
                 disconnect_at: Optional[int],
                 slow_at: Optional[int], slow_s: float,
                 timeout_s: float = 60.0) -> dict:
    """One generate stream; returns ``{"tokens", "finish",
    "disconnected", "stalled"}``. Fault knobs: ``disconnect_at`` drops
    the connection after that many token events; ``slow_at`` stops
    reading for ``slow_s`` after that many."""
    from apex_tpu.serving.http import _iter_sse

    raw = json.dumps(body).encode()
    sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    if slow_at is not None:
        # a slow reader only exerts backpressure once the kernel
        # buffers fill — shrink the receive window (must happen BEFORE
        # connect: the window scale is fixed at the handshake)
        sock.setsockopt(socket.SOL_SOCKET, socket.SO_RCVBUF, 2048)
    sock.settimeout(timeout_s)
    try:
        sock.connect((host, port))
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        sock.sendall((f"POST /v1/generate HTTP/1.1\r\nHost: x\r\n"
                      f"Content-Length: {len(raw)}\r\n"
                      f"Connection: close\r\n\r\n").encode() + raw)
        f = sock.makefile("rb")
        status_line = f.readline().decode("latin-1")
        while f.readline() not in (b"\r\n", b"\n", b""):
            pass
        parts = status_line.split(" ", 2)
        status = int(parts[1]) if len(parts) > 1 else 0
        if status != 200:
            payload = f.read().decode("utf-8", "replace")
            raise RuntimeError(f"HTTP {status} from /v1/generate: "
                               f"{payload[:200]}")
        out: dict = {"tokens": [], "finish": None,
                     "disconnected": False, "stalled": False}
        if disconnect_at is not None and disconnect_at == 0:
            sock.shutdown(socket.SHUT_RDWR)   # drop before any token
            out["disconnected"] = True
            return out
        for event, data in _iter_sse(f):
            if event == "token":
                out["tokens"].append(int(data["token"]))
                n = len(out["tokens"])
                if disconnect_at is not None and n >= disconnect_at:
                    # a REAL drop: close() would keep the fd alive under
                    # the makefile reader and the server never notices
                    sock.shutdown(socket.SHUT_RDWR)
                    out["disconnected"] = True
                    return out
                if slow_at is not None and n == slow_at:
                    out["stalled"] = True
                    time.sleep(slow_s)   # socket open, nothing read
            elif event == "done":
                out["finish"] = data.get("finish_reason")
                return out
            elif event == "error":
                raise RuntimeError(f"stream error: {data.get('error')}")
        raise RuntimeError("stream ended without a terminal event")
    finally:
        try:
            sock.close()
        except OSError:
            pass


def _torn_submit(host: str, port: int, body: dict) -> None:
    """The ``conn_reset`` fault: half a request, then an RST. The
    server must survive the torn submit (the request never reaches the
    engine); the caller retries on a fresh connection."""
    raw = json.dumps(body).encode()
    head = (f"POST /v1/generate HTTP/1.1\r\nHost: x\r\n"
            f"Content-Length: {len(raw)}\r\n\r\n").encode()
    wire = head + raw
    sock = socket.create_connection((host, port), timeout=10.0)
    try:
        sock.sendall(wire[:max(len(wire) // 2, 1)])
        # SO_LINGER(on, 0): close sends RST, not FIN — the reset the
        # fault kind is named for
        sock.setsockopt(socket.SOL_SOCKET, socket.SO_LINGER,
                        struct.pack("ii", 1, 0))
    finally:
        sock.close()


def _client(host: str, port: int, e, net, due: float,
            results: Dict[int, dict],
            errors: List[BaseException]) -> None:
    """One request's closed-loop client: wait for the arrival time,
    apply its network faults, bank what the socket delivered."""
    try:
        delay = due - time.perf_counter()
        if delay > 0:
            time.sleep(delay)
        body = {"prompt": list(e.prompt),
                "max_new_tokens": e.max_new_tokens,
                "priority": e.priority,
                "request_id": e.request_id}
        if e.deadline_ms is not None:
            body["deadline_ms"] = e.deadline_ms
        if e.tpot_slo_ms is not None:
            body["tpot_slo_ms"] = e.tpot_slo_ms
        disconnect_at = slow_at = None
        slow_s = 0.0
        retried = 0
        for spec in net:
            if spec.kind == "conn_reset":
                _torn_submit(host, port, body)
                retried += 1
            elif spec.kind == "client_disconnect":
                disconnect_at = spec.at
            elif spec.kind == "slow_reader":
                slow_at = spec.at
                slow_s = spec.delay_ms * 1e-3
        res = _post_stream(host, port, body,
                           disconnect_at=disconnect_at,
                           slow_at=slow_at, slow_s=slow_s)
        res["retried"] = retried
        results[e.request_id] = res
    except BaseException as exc:       # noqa: BLE001 — banked, re-raised
        errors.append(exc)


def replay_http(spec, trace):
    """Replay ``trace`` through a localhost HTTP server over a fresh
    threaded frontend; returns ``(outputs, stats, tracer, wall_s,
    http_block)`` — the same surface as the in-process replay plus the
    report's ``http`` block."""
    from apex_tpu.serving.faults import FaultPlan
    from apex_tpu.serving.frontend import ServingFrontend
    from apex_tpu.serving.http import HttpServingServer
    from apex_tpu.serving.kv_pool import free_page_count
    from apex_tpu.serving.policy import PriorityDeadlinePolicy
    from apex_tpu.serving.scenarios import runner

    es = spec.engine
    if es.replicas > 1:
        raise ValueError("http replay is single-replica (the router-"
                         "over-HTTP surface lives in serving/http.py's "
                         "HttpReplicaClient; see tests/test_http.py)")
    plan = FaultPlan(specs=tuple(spec.faults))
    _, model, v = runner.build_model(es.model)
    engine = runner._build_engine(spec, model, v)
    pages_total = free_page_count(engine.cache)
    policy = PriorityDeadlinePolicy(
        preempt_on_priority=es.preempt_on_priority,
        preempt_margin_ms=es.preempt_margin_ms)
    frontend = ServingFrontend(
        engine, policy=policy, fault_hook=plan.injector(0),
        backpressure_window=es.backpressure_window)
    frontend.start()
    server = HttpServingServer(
        frontend, sse_pad_bytes=es.sse_pad_bytes,
        sndbuf=es.sndbuf).start()
    results: Dict[int, dict] = {}
    errors: List[BaseException] = []
    t0 = time.perf_counter()
    try:
        threads = []
        for e in trace.events:
            due = t0 + e.arrival_ms * spec.time_scale * 1e-3
            t = threading.Thread(
                target=_client,
                args=(server.host, server.port, e,
                      plan.net_faults_for(e.request_id), due,
                      results, errors),
                name=f"scenario-http-client-{e.request_id}",
                daemon=True)
            t.start()
            threads.append(t)
        for t in threads:
            t.join(timeout=120.0)
        if any(t.is_alive() for t in threads):
            raise AssertionError(
                f"scenario {spec.name!r}: HTTP client threads hung")
        if errors:
            raise AssertionError(
                f"scenario {spec.name!r}: HTTP client failed: "
                f"{errors[0]!r}") from errors[0]
        server.drain(deadline_s=30.0)
        wall_s = time.perf_counter() - t0
        stats = frontend.stats()
        deltas = server.http_counter_deltas()
        # the no-pin/no-leak contract, checked in-band: once every
        # stream resolved, every pool page is either free or parked in
        # the radix cache — a socket pinned nothing. A disconnect's
        # cancel retires at the pump's next sync boundary, so give the
        # accounting a bounded moment to settle before declaring a leak
        deadline = time.monotonic() + 10.0
        while True:
            cached = (len(engine.prefix) if engine.prefix is not None
                      else 0)
            free_after = free_page_count(engine.cache)
            if free_after + cached == pages_total:
                break
            if time.monotonic() > deadline:
                raise AssertionError(
                    f"scenario {spec.name!r}: page leak over HTTP — "
                    f"{free_after} free + {cached} cached != "
                    f"{pages_total} total")
            time.sleep(0.01)
        http_block = {
            "streams": int(deltas["streams"]),
            "tokens": int(deltas["tokens"]),
            "disconnects": int(deltas["disconnects"]),
            "rejected": int(deltas["rejected"]),
            "errors": int(deltas["errors"]),
            "conn_reset_retries": int(sum(
                r.get("retried", 0) for r in results.values())),
            "slow_reader_stalls": int(sum(
                1 for r in results.values() if r.get("stalled"))),
            "backpressure_spills": int(
                stats.get("backpressure_spills", 0)),
            "free_pages_recovered": int(free_after),
        }
        outputs = [np.asarray(results[e.request_id]["tokens"], np.int32)
                   for e in trace.events]
        return outputs, stats, frontend.tracer, wall_s, http_block
    finally:
        server.shutdown(deadline_s=10.0)
        frontend.shutdown(deadline_s=10.0)
