"""Asyncio adapter over the thread-based serving pump.

:class:`~apex_tpu.serving.frontend.StreamHandle` is a thread-queue
object: the pump pushes tokens from its thread, consumers block in
``get()``. An asyncio server cannot block its event loop, so
:class:`AsyncStreamHandle` bridges the two worlds without adding any
thread of its own:

- it reads through the handle's lock-snapshotted ``tokens_so_far()``
  cursor-style (never the blocking queue), so the event loop never
  parks in a ``queue.Queue.get``;
- the handle's listener seam (``StreamHandle.set_listener``) fires on
  the PUMP's thread after every push/finish/fail; the adapter trampolines
  it onto the loop with ``call_soon_threadsafe`` to set one
  ``asyncio.Event`` — the only cross-thread traffic is that wake-up;
- consumption is **explicitly acked**: reading a token here does NOT
  mark it consumed. The HTTP writer calls :meth:`ack` only after
  ``await writer.drain()`` returns for that token's bytes, which is what
  ties socket backpressure to the frontend's spill window
  (``ServingFrontend(backpressure_window=...)`` — docs/http.md).

The adapter holds no sync lock across an ``await`` (the conc-lint tier's
``conc-await-under-lock`` rule binds that for the whole repo — an await
under a held ``threading.Lock`` wedges every task on the loop, including
the one that would release it).
"""

from __future__ import annotations

import asyncio
from typing import Optional

from apex_tpu.serving.frontend import StreamHandle

__all__ = ["AsyncStreamHandle"]


class AsyncStreamHandle:
    """One request's token stream as an async iterator.

    Wraps a :class:`StreamHandle` for consumption from a single asyncio
    task (one connection = one adapter = one consumer task; the adapter
    is NOT safe for concurrent ``get()`` from multiple tasks). The
    underlying handle remains fully usable — ``cancel()``/``result()``
    delegate to it.
    """

    def __init__(self, handle: StreamHandle,
                 loop: Optional[asyncio.AbstractEventLoop] = None):
        self.handle = handle
        self._loop = loop if loop is not None \
            else asyncio.get_event_loop()
        self._evt = asyncio.Event()
        self._cursor = 0                 # tokens read through get()
        handle.set_listener(self._wake)

    # -- pump-thread side ----------------------------------------------------

    def _wake(self) -> None:
        """Listener trampoline: runs on the pump thread; the only thing
        it may touch is the loop's threadsafe call queue."""
        try:
            self._loop.call_soon_threadsafe(self._evt.set)
        except RuntimeError:
            pass                         # loop already closed — nothing
        #                                  left to wake

    # -- event-loop side -----------------------------------------------------

    @property
    def request_id(self):
        return self.handle.request_id

    @property
    def done(self) -> bool:
        return self.handle.done

    @property
    def cancelled(self) -> bool:
        return self.handle.cancelled

    @property
    def error(self):
        return self.handle.error

    @property
    def cursor(self) -> int:
        """Tokens read so far (== the index to :meth:`ack` once their
        bytes are drained)."""
        return self._cursor

    def cancel(self) -> None:
        self.handle.cancel()

    def ack(self, n: Optional[int] = None) -> None:
        """Mark the first ``n`` tokens (default: everything read so
        far) consumed on the underlying handle — the backpressure
        signal. Call AFTER the transport accepted the bytes."""
        self.handle.ack(self._cursor if n is None else n)

    async def get(self) -> Optional[int]:
        """Next token, or None once the stream terminated; raises the
        terminal :class:`~apex_tpu.serving.frontend.ServingError` if the
        request failed. Never blocks the event loop."""
        while True:
            toks = self.handle.tokens_so_far()
            if self._cursor < len(toks):
                tok = toks[self._cursor]
                self._cursor += 1
                return int(tok)
            if self.handle.done:
                err = self.handle.error
                if err is not None:
                    raise err
                return None
            self._evt.clear()
            # close the set-before-clear race: a push between the
            # snapshot above and the clear would otherwise be lost
            if (len(self.handle.tokens_so_far()) > self._cursor
                    or self.handle.done):
                continue
            await self._evt.wait()

    def __aiter__(self):
        return self

    async def __anext__(self) -> int:
        tok = await self.get()
        if tok is None:
            raise StopAsyncIteration
        return tok

    async def wait_done(self, timeout: Optional[float] = None) -> bool:
        """Await stream termination (True) or timeout (False) without
        blocking the loop."""
        deadline = (self._loop.time() + timeout
                    if timeout is not None else None)
        while not self.handle.done:
            self._evt.clear()
            if self.handle.done:
                break
            if deadline is None:
                await self._evt.wait()
                continue
            left = deadline - self._loop.time()
            if left <= 0:
                return False
            try:
                await asyncio.wait_for(self._evt.wait(), left)
            except asyncio.TimeoutError:
                return False
        return True
