"""Asyncio HTTP/SSE serving surface over the thread-based pump.

Stdlib-only (``asyncio`` + sockets — no third-party server, matching the
repo's no-new-deps stance): :class:`HttpServingServer` exposes

- ``POST /v1/generate`` — submit one request, stream its tokens back as
  Server-Sent Events (``event: start`` with the request id, one
  ``event: token`` per generated token, a terminal ``event: done`` or
  ``event: error``; the connection closes after the stream —
  ``Connection: close`` framing, docs/http.md);
- ``POST /v1/cancel/<request_id>`` — cancel a live stream (the request
  retires at its next sync boundary; the SSE stream terminates with
  ``finish_reason: "cancelled"``);
- ``GET /healthz`` / ``/metrics`` / ``/metrics.json`` / ``/costs`` — the
  observability endpoints ``apex_tpu.obs.export`` has always served,
  unified on the serving port (``health_doc`` grows an ``http`` block
  and — when the target is a router — the per-replica block).

The robustness contract (the reason this layer exists):

- **Backpressure feeds admission.** The SSE writer acks a token's
  consumption (``StreamHandle.ack``) only after ``await writer.drain()``
  returned for its bytes, so a reader that stalls past the frontend's
  ``backpressure_window`` gets its slot spilled through the preemption
  path — pages into the radix cache, resume on consumption. Pool pages
  are never pinned by a socket.
- **Disconnect-safe streaming.** A watch task reads the connection; EOF
  or a reset cancels the request at the next sync boundary and every
  page frees through the normal retire path.
- **Timeouts map to the deadline machinery.** ``ttft_timeout_s`` is
  folded into ``Request.deadline_ms`` (so a miss counts in
  ``serving.deadline_misses``); wall ``timeout_s`` cancels the stream
  with ``finish_reason: "timeout"``.
- **Overload is explicit.** A router's
  :class:`~apex_tpu.serving.router.OverloadError` (or the server's own
  ``max_queue_depth`` bound) becomes HTTP 429 with ``Retry-After``.
- **Graceful drain.** ``server.drain()`` stops accepting generates
  (503), lets active streams finish (cancelling stragglers at the
  deadline), then the socket closes — the SIGTERM path.

:class:`HttpReplicaClient` is the same transport from the other side: a
frontend-SHAPED client (submit/queue_depth/failure/pump/shutdown plus
engine/tracer shims) that a :class:`~apex_tpu.serving.router.
ReplicaRouter` can supervise exactly like an in-process replica — the
ROADMAP item-3 process boundary in minimal form: router-as-client
against N HTTP replicas, failover folding delivered tokens into the
resubmission, token-identically.

Concurrency coloring (the conc-lint tier checks this file): the event
loop runs on one thread (``serving-http-loop``); coroutines are asyncio
tasks — await points are interleaving points, and the shared server
state that submit/cancel/drain touch from OTHER threads is guarded by a
``threading.Lock`` that is never held across an ``await``
(``conc-await-under-lock``).
"""

from __future__ import annotations

import asyncio
import dataclasses
import json
import socket
import threading
import time
from typing import Dict, Optional

import numpy as np

from apex_tpu.obs import export as obs_export
from apex_tpu.obs import fleet
from apex_tpu.obs.spans import SpanTracer
from apex_tpu.serving.aio import AsyncStreamHandle
from apex_tpu.serving.frontend import ServingError, StreamHandle
from apex_tpu.serving.router import OverloadError
from apex_tpu.serving.scheduler import _RUN_COUNTERS, Request
from apex_tpu.utils import metrics

__all__ = ["HttpServingServer", "HttpReplicaClient"]

_HTTP_COUNTERS = ("requests", "streams", "tokens", "disconnects",
                  "timeouts", "rejected", "cancelled", "errors")


def _json_bytes(doc) -> bytes:
    return (json.dumps(doc, sort_keys=True) + "\n").encode()


class HttpServingServer:
    """One port, one event loop (on its own daemon thread), one serving
    target — a :class:`~apex_tpu.serving.frontend.ServingFrontend` or a
    :class:`~apex_tpu.serving.router.ReplicaRouter` (detected by its
    ``replicas`` attribute; router submits carry the body's
    ``affinity_key``). The server does NOT own the target: start the
    frontend's pump (``frontend.start()``) / the router's supervisor
    before serving, and shut them down after ``server.shutdown()``.

    ``sse_pad_bytes``/``sndbuf`` shrink the transport's elasticity so
    socket backpressure reaches the frontend window quickly — chaos
    scenarios use them; production defaults leave the kernel alone.
    """

    def __init__(self, target, *, host: str = "127.0.0.1", port: int = 0,
                 max_queue_depth: Optional[int] = None,
                 retry_after_s: float = 0.05,
                 default_timeout_s: Optional[float] = None,
                 sse_pad_bytes: int = 0, sndbuf: Optional[int] = None):
        self.target = target
        self.host = host
        self._want_port = port
        self.port: Optional[int] = None
        self.is_router = hasattr(target, "replicas")
        self.max_queue_depth = max_queue_depth
        self.retry_after_s = retry_after_s
        self.default_timeout_s = default_timeout_s
        self.sse_pad_bytes = sse_pad_bytes
        self.sndbuf = sndbuf
        # cross-thread server state: the loop thread, submit-side
        # threads (cancel endpoint bookkeeping), and drain()/close()
        # callers all touch these — one lock, NEVER held across an await
        self._lock = threading.Lock()
        self._streams: Dict[str, StreamHandle] = {}
        self._draining = False
        self._thread: Optional[threading.Thread] = None
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._server = None
        self._boot_error: Optional[BaseException] = None
        self._C = {name: metrics.counter(f"http.{name}")
                   for name in _HTTP_COUNTERS}
        self._c0 = {name: c.value for name, c in self._C.items()}
        self._g_conns = metrics.gauge("http.connections")
        self._g_streams = metrics.gauge("http.streams_active")
        self._g_unread = metrics.gauge("http.stream_unread")
        self._n_conns = 0

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> "HttpServingServer":
        """Bind and serve on a background event-loop thread; returns
        once the port is bound (read it from ``self.port``)."""
        if self._thread is not None:
            raise RuntimeError("server already started")
        ready = threading.Event()
        self._thread = threading.Thread(target=self._run, args=(ready,),
                                        name="serving-http-loop",
                                        daemon=True)
        self._thread.start()
        ready.wait()
        if self._boot_error is not None:
            self._thread.join()
            self._thread = None
            raise self._boot_error
        return self

    def _run(self, ready: threading.Event) -> None:
        loop = asyncio.new_event_loop()
        asyncio.set_event_loop(loop)
        self._loop = loop
        try:
            self._server = loop.run_until_complete(asyncio.start_server(
                self._handle, self.host, self._want_port))
            self.port = self._server.sockets[0].getsockname()[1]
        except BaseException as exc:     # noqa: BLE001 — boot surface
            self._boot_error = exc
            ready.set()
            loop.close()
            return
        ready.set()
        try:
            loop.run_forever()
        finally:
            # zero-dangling-tasks contract: every connection task is
            # cancelled, awaited, and the loop closed before the thread
            # exits
            pending = asyncio.all_tasks(loop)
            for task in pending:
                task.cancel()
            if pending:
                loop.run_until_complete(asyncio.gather(
                    *pending, return_exceptions=True))
            loop.run_until_complete(loop.shutdown_asyncgens())
            loop.close()

    def drain(self, deadline_s: float = 30.0) -> None:
        """Graceful drain: stop accepting ``/v1/generate`` (503 with
        ``Retry-After``), let active streams finish, cancel the
        stragglers once ``deadline_s`` expires, and return when every
        stream resolved (observability endpoints keep serving)."""
        with self._lock:
            self._draining = True
        deadline = time.monotonic() + deadline_s
        cancelled = False
        while True:
            with self._lock:
                live = list(self._streams.values())
            if not live:
                return
            if not cancelled and time.monotonic() >= deadline:
                for handle in live:
                    handle.cancel()
                cancelled = True
                deadline = time.monotonic() + max(deadline_s, 1.0)
            if cancelled and time.monotonic() >= deadline:
                return                   # handles cancelled; streams
            #                              resolve at the pump's pace
            time.sleep(0.005)

    def close(self) -> None:
        """Stop the listener, cancel every connection task, stop the
        loop, and join the thread. Live streams terminate (their
        handles are cancelled so the pump releases their pages)."""
        if self._thread is None:
            return
        with self._lock:
            self._draining = True
            live = list(self._streams.values())
        for handle in live:
            handle.cancel()
        loop = self._loop

        def _stop():
            if self._server is not None:
                self._server.close()
            loop.stop()

        loop.call_soon_threadsafe(_stop)
        self._thread.join(timeout=10.0)
        self._thread = None

    def shutdown(self, deadline_s: float = 30.0) -> None:
        """``drain()`` then ``close()`` — the SIGTERM path."""
        self.drain(deadline_s)
        self.close()

    # -- metrics / health ----------------------------------------------------

    def http_counter_deltas(self) -> Dict[str, float]:
        return {name: c.value - self._c0[name]
                for name, c in self._C.items()}

    def _http_block(self) -> dict:
        with self._lock:
            streams = len(self._streams)
            draining = self._draining
            conns = self._n_conns
        return {"streams_active": streams, "draining": draining,
                "connections": conns,
                **{name: int(c.value - self._c0[name])
                   for name, c in self._C.items()}}

    def _queue_depth(self) -> int:
        if self.is_router:
            return sum(rep.frontend.queue_depth
                       for rep in self.target.replicas if rep.alive)
        return self.target.queue_depth

    def _health_doc(self) -> dict:
        if self.is_router:
            doc = obs_export.health_doc(router=self.target)
            eng = self.target.replicas[0].frontend.engine
        else:
            doc = obs_export.health_doc(frontend=self.target)
            eng = self.target.engine
        doc["http"] = self._http_block()
        doc["http"]["eos_token_id"] = eng.eos_token_id
        return doc

    # -- connection handling -------------------------------------------------

    async def _handle(self, reader: asyncio.StreamReader,
                      writer: asyncio.StreamWriter) -> None:
        with self._lock:
            self._n_conns += 1
            self._g_conns.set(self._n_conns)
        try:
            sock = writer.get_extra_info("socket")
            if sock is not None:
                sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                if self.sndbuf is not None:
                    sock.setsockopt(socket.SOL_SOCKET, socket.SO_SNDBUF,
                                    self.sndbuf)
            if self.sndbuf is not None:
                # make drain() track the kernel, not an elastic user-
                # space buffer — the chaos scenarios' backpressure knob
                writer.transport.set_write_buffer_limits(high=0)
            await self._dispatch(reader, writer)
        except (asyncio.CancelledError, ConnectionError,
                asyncio.IncompleteReadError):
            pass                         # peer went away / shutdown
        finally:
            with self._lock:
                self._n_conns -= 1
                self._g_conns.set(self._n_conns)
            writer.close()
            try:
                await writer.wait_closed()
            except (ConnectionError, asyncio.CancelledError):
                pass

    async def _dispatch(self, reader, writer) -> None:
        line = await reader.readline()
        if not line:
            return
        try:
            method, path, _ = line.decode("latin-1").split(" ", 2)
        except ValueError:
            await self._resp(writer, 400, _json_bytes(
                {"error": "malformed request line"}))
            return
        headers = {}
        while True:
            h = await reader.readline()
            if h in (b"\r\n", b"\n", b""):
                break
            key, _, val = h.decode("latin-1").partition(":")
            headers[key.strip().lower()] = val.strip()
        body = b""
        clen = int(headers.get("content-length", "0") or 0)
        if clen:
            body = await reader.readexactly(clen)
        path, _, query = path.partition("?")
        if method == "POST" and path == "/v1/generate":
            await self._generate(reader, writer, body, headers)
        elif method == "POST" and path.startswith("/v1/cancel/"):
            await self._cancel(writer, path[len("/v1/cancel/"):])
        elif method == "GET" and path == "/events":
            await self._events(writer, query)
        elif method == "GET" and path == "/healthz":
            await self._resp(writer, 200, _json_bytes(self._health_doc()))
        elif method == "GET" and path in ("/metrics", "/"):
            await self._resp(
                writer, 200, obs_export.prometheus_text().encode(),
                ctype="text/plain; version=0.0.4; charset=utf-8")
        elif method == "GET" and path == "/metrics.json":
            await self._resp(writer, 200,
                             _json_bytes(obs_export.json_snapshot()))
        elif method == "GET" and path == "/costs":
            doc = obs_export.latest_costs()
            if doc is None:
                await self._resp(writer, 404, _json_bytes(
                    {"error": "no cost snapshot published"}))
            else:
                await self._resp(writer, 200, _json_bytes(doc))
        else:
            await self._resp(writer, 404, _json_bytes(
                {"error": f"no route {method} {path}"}))

    async def _resp(self, writer, status: int, body: bytes,
                    ctype: str = "application/json",
                    extra=()) -> None:
        reason = {200: "OK", 400: "Bad Request", 404: "Not Found",
                  429: "Too Many Requests", 500: "Internal Server Error",
                  503: "Service Unavailable"}.get(status, "?")
        head = [f"HTTP/1.1 {status} {reason}",
                f"Content-Type: {ctype}",
                f"Content-Length: {len(body)}",
                "Connection: close"]
        head.extend(extra)
        writer.write(("\r\n".join(head) + "\r\n\r\n").encode() + body)
        await writer.drain()

    async def _events(self, writer, query: str) -> None:
        """``GET /events?since_seq=N`` — the replica's event ring as an
        incremental, cursor-based read (the federation scrape's second
        endpoint): events past the cursor plus the count the ring
        lapped past it (``dropped`` — the scraper's gap detector)."""
        since = -1
        for part in query.split("&"):
            key, _, val = part.partition("=")
            if key == "since_seq" and val:
                try:
                    since = int(val)
                except ValueError:
                    await self._resp(writer, 400, _json_bytes(
                        {"error": f"since_seq must be an integer, "
                                  f"got {val!r}"}))
                    return
        log = self.target.events if self.is_router \
            else self.target.engine.events
        events, dropped = log.since(since)
        await self._resp(writer, 200, _json_bytes(
            {"kind": "event_log", "capacity": log.capacity,
             "total": log.total, "dropped": dropped,
             "since_seq": since, "events": events}))

    async def _cancel(self, writer, request_id: str) -> None:
        with self._lock:
            handle = self._streams.get(request_id)
        if handle is None:
            await self._resp(writer, 404, _json_bytes(
                {"error": f"no live stream {request_id!r}"}))
            return
        handle.cancel()
        self._C["cancelled"].inc()
        await self._resp(writer, 200, _json_bytes(
            {"ok": True, "request_id": request_id}))

    # -- the generate stream -------------------------------------------------

    def _submit(self, body: dict, headers: Optional[dict] = None):
        """Parse + submit (sync — the frontend's submit path is
        non-blocking bookkeeping). Returns ``(handle, request_id)``;
        raises ValueError (400), OverloadError (429), ServingError
        (503)."""
        prompt = body.get("prompt")
        if not isinstance(prompt, list) or not prompt:
            raise ValueError("body.prompt must be a non-empty token list")
        deadline_ms = body.get("deadline_ms")
        ttft_timeout_s = body.get("ttft_timeout_s")
        if ttft_timeout_s is not None:
            # the TTFT timeout IS a deadline: fold it into the deadline
            # machinery so a miss lands in serving.deadline_misses
            ttft_ms = float(ttft_timeout_s) * 1e3
            deadline_ms = ttft_ms if deadline_ms is None \
                else min(float(deadline_ms), ttft_ms)
        # trace propagation: the traceparent header (or a bare body
        # trace_id) carries the caller's fleet-wide trace into this
        # replica's Request, so the local tracer's spans stitch with
        # the router side's; absent/malformed degrades to a local mint
        # downstream, never to a 400
        trace_id = fleet.parse_traceparent(
            (headers or {}).get("traceparent")) \
            or fleet.parse_traceparent(body.get("trace_id"))
        req = Request(prompt=np.asarray(prompt, np.int32),
                      max_new_tokens=int(body.get("max_new_tokens", 16)),
                      priority=int(body.get("priority", 0)),
                      deadline_ms=deadline_ms,
                      tpot_slo_ms=body.get("tpot_slo_ms"),
                      trace_id=trace_id)
        if self.max_queue_depth is not None:
            depth = self._queue_depth()
            if depth >= self.max_queue_depth:
                raise OverloadError(
                    f"queue depth {depth} >= {self.max_queue_depth}",
                    retry_after_s=self.retry_after_s)
        request_id = body.get("request_id")
        if request_id is not None:
            try:
                # the frontend contract: ids are ints (they seed the
                # request's sampling stream via fold_in)
                request_id = int(request_id)
            except (TypeError, ValueError):
                raise ValueError(
                    f"request_id must be an integer, got {request_id!r}")
        if self.is_router:
            handle = self.target.submit(
                req, request_id=request_id,
                affinity_key=body.get("affinity_key"))
        else:
            handle = self.target.submit(req, request_id=request_id)
        return handle, str(handle.request_id)

    async def _generate(self, reader, writer, raw: bytes,
                        headers: Optional[dict] = None) -> None:
        self._C["requests"].inc()
        with self._lock:
            draining = self._draining
        if draining:
            await self._resp(
                writer, 503, _json_bytes({"error": "draining"}),
                extra=(f"Retry-After: {max(self.retry_after_s, 1.0):g}",))
            return
        try:
            body = json.loads(raw.decode() or "{}")
            if not isinstance(body, dict):
                raise ValueError("body must be a JSON object")
            handle, rid = self._submit(body, headers)
        except OverloadError as exc:
            self._C["rejected"].inc()
            retry = getattr(exc, "retry_after_s", self.retry_after_s)
            await self._resp(writer, 429,
                             _json_bytes({"error": str(exc),
                                          "retry_after_s": retry}),
                             extra=(f"Retry-After: {retry:g}",))
            return
        except (ValueError, json.JSONDecodeError) as exc:
            await self._resp(writer, 400,
                             _json_bytes({"error": str(exc)}))
            return
        except ServingError as exc:
            await self._resp(writer, 503,
                             _json_bytes({"error": str(exc)}))
            return
        with self._lock:
            self._streams[rid] = handle
            self._g_streams.set(len(self._streams))
        self._C["streams"].inc()
        watcher = None
        try:
            loop = asyncio.get_event_loop()
            ah = AsyncStreamHandle(handle, loop)
            head = ["HTTP/1.1 200 OK",
                    "Content-Type: text/event-stream",
                    "Cache-Control: no-cache",
                    "Connection: close"]
            writer.write(("\r\n".join(head) + "\r\n\r\n").encode())
            await writer.drain()
            watcher = loop.create_task(
                self._watch_disconnect(reader, handle))
            await self._stream_tokens(writer, handle, ah, body, rid)
        finally:
            if watcher is not None:
                watcher.cancel()
            # belt-and-braces: whatever path ended the stream, the
            # handle must not keep pages pinned — cancel is idempotent
            # and a no-op on a finished request
            if not handle.done:
                handle.cancel()
            with self._lock:
                self._streams.pop(rid, None)
                self._g_streams.set(len(self._streams))

    async def _watch_disconnect(self, reader, handle) -> None:
        """Read the (request-complete) connection: EOF or an error means
        the client went away — cancel at the next sync boundary so every
        page frees. Cancelled (by the stream finishing) without ever
        firing on a healthy connection."""
        try:
            await reader.read(1)
        except asyncio.CancelledError:
            raise
        except Exception:                # noqa: BLE001 — reset == gone
            pass
        if not handle.done:
            handle.cancel()
            self._C["disconnects"].inc()

    async def _sse(self, writer, event: str, data: dict) -> None:
        lines = [f"event: {event}", f"data: {json.dumps(data, sort_keys=True)}"]
        if self.sse_pad_bytes:
            lines.append(":" + "p" * self.sse_pad_bytes)
        writer.write(("\n".join(lines) + "\n\n").encode())
        await writer.drain()

    async def _stream_tokens(self, writer, handle, ah, body: dict,
                             rid: str) -> None:
        loop = asyncio.get_event_loop()
        timeout_s = body.get("timeout_s", self.default_timeout_s)
        ttft_timeout_s = body.get("ttft_timeout_s")
        t0 = loop.time()
        wall_dl = t0 + float(timeout_s) if timeout_s is not None else None
        ttft_dl = t0 + float(ttft_timeout_s) \
            if ttft_timeout_s is not None else None
        n = 0
        finish = "stop"
        try:
            # the `start` frame is informational preamble; both in-repo
            # clients key on token/done/error and skip unknown events,
            # per the SSE spec. Kept for curl users and future clients.
            # tpu-lint: disable=contract-endpoint-undocumented -- see above
            await self._sse(writer, "start", {"request_id": rid})
            while True:
                dl = ttft_dl if (n == 0 and ttft_dl is not None) \
                    else wall_dl
                try:
                    if dl is None:
                        tok = await ah.get()
                    else:
                        left = dl - loop.time()
                        if left <= 0:
                            raise asyncio.TimeoutError
                        tok = await asyncio.wait_for(ah.get(), left)
                except asyncio.TimeoutError:
                    finish = "timeout"
                    self._C["timeouts"].inc()
                    handle.cancel()
                    break
                if tok is None:
                    finish = "cancelled" if handle.cancelled else "stop"
                    break
                await self._sse(writer, "token",
                                {"token": tok, "index": n})
                n += 1
                # consumption = the transport accepted the bytes (drain
                # returned). A stalled reader stops this ack, unread()
                # grows, and the frontend spills the slot.
                ah.ack()
                self._C["tokens"].inc()
                self._g_unread.set(handle.unread())
            await self._sse(writer, "done", {
                "request_id": rid, "finish_reason": finish,
                "completion_tokens": n})
        except ServingError as exc:
            self._C["errors"].inc()
            try:
                await self._sse(writer, "error",
                                {"request_id": rid, "error": str(exc)})
            except (ConnectionError, asyncio.CancelledError):
                pass
        except (ConnectionError, asyncio.CancelledError):
            # the peer vanished mid-write — the watcher (or the finally
            # in _generate) cancels the handle; nothing to send to
            raise


# ---------------------------------------------------------------------------
# router-as-client: the frontend-shaped HTTP replica
# ---------------------------------------------------------------------------


class _ClientEngineShim:
    """The slice of the engine surface a
    :class:`~apex_tpu.serving.router.ReplicaRouter` touches on replica
    0: request validation (delegated to the server — a bad request
    fails its stream with 400) and ``eos_token_id`` (for the router's
    resume-request fold)."""

    def __init__(self, eos_token_id=None):
        self.eos_token_id = eos_token_id

    def _validate_request(self, request) -> None:
        if request.max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")


class _ClientHandle(StreamHandle):
    """The client-side stream handle: ``cancel()`` additionally tears
    down the socket, which the server's disconnect watcher turns into a
    server-side cancel — the wire form of the in-process contract."""

    def __init__(self, request_id):
        super().__init__(request_id)
        self._sock: Optional[socket.socket] = None

    def cancel(self) -> None:
        super().cancel()
        sock = self._sock
        if sock is not None:
            try:
                sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass


class HttpReplicaClient:
    """One remote HTTP replica, wearing the frontend surface the router
    supervises: ``submit`` opens one streaming connection per request on
    a short-lived reader thread, tokens land in a local
    :class:`StreamHandle` (so the router's forwarding/failover reads
    ``tokens_so_far()`` exactly as in-process), and a transport-level
    failure publishes ``failure`` — the supervisor marks the replica
    dead and re-homes its in-flight requests with their delivered
    tokens folded in, token-identically on the survivor.

    Counter aggregation is server-side (scrape ``/metrics``);
    ``counter_deltas()`` reports zeros so ``router.stats()`` stays
    well-formed across the process boundary (docs/http.md Limits)."""

    def __init__(self, host: str, port: int, *, eos_token_id=None,
                 connect_timeout_s: float = 5.0):
        self.host = host
        self.port = port
        self.connect_timeout_s = connect_timeout_s
        self.engine = _ClientEngineShim(eos_token_id)
        self.tracer = SpanTracer()
        self.fault_hook = None
        self._lock = threading.Lock()
        self._live: Dict[object, _ClientHandle] = {}
        self._threads: Dict[object, threading.Thread] = {}
        self._failure: Optional[BaseException] = None
        self._accepting = True
        self._seq = 0

    # -- frontend surface ----------------------------------------------------

    @property
    def queue_depth(self) -> int:
        with self._lock:
            return len(self._live)

    @property
    def pump_alive(self) -> bool:
        with self._lock:
            return self._failure is None and self._accepting

    @property
    def failure(self) -> Optional[BaseException]:
        with self._lock:
            return self._failure

    def submit(self, request: Request, *,
               request_id=None) -> StreamHandle:
        self.engine._validate_request(request)
        if request.trace_id is None:
            # a direct client submit mints its own trace id (the router
            # mints before it reaches us) — minted HERE so the wire
            # request carries it and the server tags the same trace
            request = dataclasses.replace(
                request, trace_id=fleet.mint_trace_id())
        with self._lock:
            if self._failure is not None:
                raise ServingError("http replica has failed") \
                    from self._failure
            if not self._accepting:
                raise ServingError("http replica client is shut down")
            if request_id is None:
                request_id = self._seq
            self._seq += 1
            handle = _ClientHandle(request_id)
            self._live[request_id] = handle
            thread = threading.Thread(
                target=self._stream, args=(request, request_id, handle),
                name=f"http-replica-stream-{request_id}", daemon=True)
            self._threads[request_id] = thread
        # the client-side enqueue binds this request to its fleet-wide
        # trace — the span dump this tracer produces is one of the
        # inputs stitch_traces() joins across replicas
        self.tracer.event(request_id, "enqueue",
                          prompt_tokens=int(np.asarray(
                              request.prompt).reshape(-1).shape[0]),
                          max_new_tokens=request.max_new_tokens,
                          priority=request.priority,
                          deadline_ms=request.deadline_ms,
                          trace_id=request.trace_id)
        thread.start()
        return handle

    def pump(self) -> bool:
        """No local pump — the remote server drives itself; report
        whether streams are still in flight so ``router.drain()``
        keeps ticking."""
        with self._lock:
            return bool(self._live)

    def start(self) -> None:
        pass                             # the remote pump is remote

    def stop(self, timeout: Optional[float] = None) -> None:
        pass                             # nothing local to stop

    def counter_deltas(self) -> Dict[str, float]:
        return {name: 0.0 for name in _RUN_COUNTERS}

    # -- fleet scrape (blocking; caller must hold NO lock) --------------------

    def _get_json(self, path: str) -> dict:
        """Blocking GET against the remote replica; returns the parsed
        JSON body.  Raises :class:`ServingError` on connect failure or a
        non-200 status — the fleet collector treats that as a missed
        scrape, not a fatal error."""
        sock = socket.create_connection(
            (self.host, self.port), timeout=self.connect_timeout_s)
        try:
            sock.sendall((f"GET {path} HTTP/1.1\r\n"
                          f"Host: {self.host}:{self.port}\r\n"
                          f"Connection: close\r\n\r\n").encode())
            f = sock.makefile("rb")
            status_line = f.readline().decode("ascii", "replace")
            parts = status_line.split(" ", 2)
            status = int(parts[1]) if len(parts) > 1 else 0
            while True:                  # headers; Connection: close ⇒
                line = f.readline()      # body runs to EOF
                if line in (b"\r\n", b"\n", b""):
                    break
            body = f.read()
            if status != 200:
                raise ServingError(
                    f"scrape GET {path} -> {status}: {body[:200]!r}")
            return json.loads(body.decode())
        finally:
            sock.close()

    def fleet_scrape(self, since_seq: int = -1) -> dict:
        """One federation scrape: the replica's metrics snapshot plus its
        event ring past ``since_seq``.  Shape is consumed by
        :func:`apex_tpu.obs.fleet.FleetCollector.tick`."""
        return {
            "metrics": self._get_json("/metrics.json"),
            "events": self._get_json(f"/events?since_seq={since_seq}"),
        }

    def shutdown(self, deadline_s: float = 30.0, *,
                 mode: str = "drain") -> None:
        with self._lock:
            self._accepting = False
            live = list(self._live.values())
            threads = list(self._threads.values())
        if mode == "cancel":
            for handle in live:
                handle.cancel()
        deadline = time.monotonic() + deadline_s
        for thread in threads:
            thread.join(max(deadline - time.monotonic(), 0.05))
        with self._lock:
            live = list(self._live.values())
        for handle in live:              # stragglers past the deadline
            handle.cancel()
            handle._fail(ServingError(
                "http replica client shutdown with stream unresolved"))

    # -- the per-request stream thread ---------------------------------------

    def _mark_failed(self, exc: BaseException) -> None:
        with self._lock:
            if self._failure is None:
                self._failure = exc if isinstance(exc, ServingError) \
                    else ServingError(f"http replica failed: {exc!r}")

    def _finish_stream(self, request_id) -> None:
        with self._lock:
            self._live.pop(request_id, None)
            self._threads.pop(request_id, None)

    def _stream(self, request, request_id, handle: _ClientHandle) -> None:
        tr = self.tracer
        sock = None
        started_decode = False
        try:
            sock = socket.create_connection(
                (self.host, self.port), timeout=self.connect_timeout_s)
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            handle._sock = sock
            body = json.dumps({
                "prompt": [int(t) for t in
                           np.asarray(request.prompt).reshape(-1)],
                "max_new_tokens": int(request.max_new_tokens),
                "priority": int(request.priority),
                "deadline_ms": request.deadline_ms,
                "tpot_slo_ms": request.tpot_slo_ms,
                "request_id": str(request_id),
                "trace_id": request.trace_id,
            }).encode()
            trace_hdr = "" if request.trace_id is None else \
                (f"traceparent: "
                 f"{fleet.traceparent(request.trace_id)}\r\n")
            head = (f"POST /v1/generate HTTP/1.1\r\n"
                    f"Host: {self.host}:{self.port}\r\n"
                    f"Content-Type: application/json\r\n"
                    f"Content-Length: {len(body)}\r\n"
                    f"{trace_hdr}"
                    f"Connection: close\r\n\r\n").encode()
            sock.sendall(head + body)
            sock.settimeout(None)        # SSE streams at the pump's pace
            f = sock.makefile("rb")
            status_line = f.readline().decode("latin-1")
            parts = status_line.split(" ", 2)
            status = int(parts[1]) if len(parts) >= 2 else 0
            while True:                  # skip response headers
                h = f.readline()
                if h in (b"\r\n", b"\n", b""):
                    break
            if status != 200:
                payload = f.read()
                exc = ServingError(
                    f"http replica returned {status}: "
                    f"{payload.decode(errors='replace')[:200]}")
                handle._fail(exc)
                if status not in (400, 429):
                    self._mark_failed(exc)
                return
            ended = False
            for event, data in _iter_sse(f):
                if event == "token":
                    tok = int(data["token"])
                    if not started_decode:
                        started_decode = True
                        tr.event(request_id, "admit", remote=True)
                        tr.event(request_id, "first_token")
                        tr.begin(request_id, "decode")
                    handle._push(tok)
                elif event == "done":
                    if started_decode:
                        tr.end(request_id, "decode",
                               new_tokens=len(handle.tokens_so_far()))
                    tr.event(request_id, "retire",
                             finish_reason=data.get("finish_reason"))
                    handle._finish(np.asarray(handle.tokens_so_far(),
                                              np.int32))
                    ended = True
                    break
                elif event == "error":
                    exc = ServingError(
                        f"remote stream failed: {data.get('error')}")
                    handle._fail(exc)
                    self._mark_failed(exc)
                    ended = True
                    break
            if not ended:
                # connection dropped mid-stream without a terminal event
                raise ConnectionError("stream ended without done/error")
        except Exception as exc:         # noqa: BLE001 — transport edge
            if handle.cancelled and not handle.done:
                # our own cancel tore the socket down — terminate the
                # stream with the truncated output, like in-process
                if started_decode:
                    tr.end(request_id, "decode",
                           new_tokens=len(handle.tokens_so_far()))
                tr.event(request_id, "retire", cancelled=True)
                handle._finish(np.asarray(handle.tokens_so_far(),
                                          np.int32))
            elif not handle.done:
                wrapped = ServingError(
                    f"http replica stream {request_id!r} failed: "
                    f"{exc!r}")
                handle._fail(wrapped)
                self._mark_failed(wrapped)
        finally:
            if sock is not None:
                try:
                    sock.close()
                except OSError:
                    pass
            self._finish_stream(request_id)


def _iter_sse(f):
    """Minimal SSE parser over a binary file-like: yields
    ``(event, data_dict)`` per event block; comment lines (padding)
    skipped; returns on EOF."""
    event, data = None, None
    for raw in f:
        line = raw.decode("utf-8", errors="replace").rstrip("\r\n")
        if not line:
            if event is not None and data is not None:
                yield event, json.loads(data)
            event, data = None, None
            continue
        if line.startswith(":"):
            continue
        if line.startswith("event:"):
            event = line[len("event:"):].strip()
        elif line.startswith("data:"):
            data = line[len("data:"):].strip()
