"""Deterministic, seeded fault injection for the serving stack.

Chaos engineering only earns its keep when the chaos is *replayable*: a
failure scenario that cannot be re-run bit-for-bit cannot pin a recovery
contract. This module makes injected failures first-class scenario
inputs:

- a :class:`FaultSpec` names ONE fault — which replica, which kind, and
  a deterministic trigger index (a pump-iteration count for
  ``kill_replica``/``pump_stall``, a submission count for
  ``admission_reject``) — and is JSON-round-trippable, so chaos
  scenarios carry their fault plans inside their
  :class:`~apex_tpu.serving.scenarios.runner.ScenarioSpec` exactly like
  arrival processes carry their rates;
- a :class:`FaultPlan` bundles specs (``FaultPlan.random(seed, ...)``
  samples one from a ``default_rng(seed)`` — seeded chaos, same seed =
  same kills);
- a :class:`FaultInjector` delivers one replica's faults through the
  frontend's **first-class seams** (``ServingFrontend(fault_hook=...)``:
  ``on_pump`` at the top of every pump iteration, ``on_submit`` before a
  submission lands) rather than monkeypatching — the injected kill takes
  the *real* pump-death path (`_fail_all`, terminal
  :class:`~apex_tpu.serving.frontend.ServingError` on every handle), so
  a chaos test exercises exactly the machinery a production fault would.

Fault kinds:

- ``kill_replica`` — the pump raises :class:`InjectedFault` at its
  ``at``-th iteration: the engine is dead mid-decode, every live handle
  on it fails terminally, and the router's supervisor must re-home its
  in-flight requests.
- ``pump_stall`` — the pump sleeps ``delay_ms`` for ``count``
  iterations starting at ``at``: a wedged-but-alive engine (GC pause,
  host contention) — latency, not death; nothing may hang or leak.
- ``admission_reject`` — ``count`` submissions starting at the
  ``at``-th raise :class:`~apex_tpu.serving.frontend.ServingError`
  from ``submit()``: an overloaded/refusing replica; the router retries
  elsewhere.
- ``slow_consumer`` — the router's token forwarding for every request
  delays ``delay_ms`` per tick (``consume_delay_s``): a slow client;
  streams must stay ordered and the pump unblocked (handles buffer,
  pages never pin on consumption).

Network fault kinds (applied CLIENT-side, by the HTTP load driver in
``scenarios/http_driver.py`` — they model the wire, so they never go
through the frontend's ``fault_hook`` seams):

- ``client_disconnect`` — the client reads ``at`` token events, then
  drops the connection (RST): the server must cancel at the next sync
  boundary and free every page; the request's output is a prefix.
- ``slow_reader`` — the client reads ``at`` tokens, then stops reading
  (socket open, recv window filling) for ``delay_ms``, then resumes to
  completion: the frontend's backpressure window must spill the slot
  (pages into the radix cache) and resume it on consumption,
  token-identically.
- ``conn_reset`` — the connection resets mid-submission (partial
  request then abort): the request never reaches the engine; the
  driver retries once on a fresh connection (client-level retry — the
  server must survive the torn request without leaking the
  connection).

For network kinds ``replica`` keeps its meaning (which server, 0 for a
single-replica scenario) and the fault applies to request ids
``{0, …, count-1}`` — deterministic, so chaos checks know exactly which
outputs are prefixes (``FaultPlan.net_faults_for``).
"""

from __future__ import annotations

import dataclasses
import json
import threading
import time
from typing import List, Optional, Sequence, Tuple

from apex_tpu.serving.frontend import ServingError

__all__ = ["FAULT_KINDS", "NETWORK_FAULT_KINDS", "FaultSpec",
           "FaultPlan", "FaultInjector", "InjectedFault"]

FAULT_KINDS = ("kill_replica", "pump_stall", "admission_reject",
               "slow_consumer", "client_disconnect", "slow_reader",
               "conn_reset")

#: the client-side kinds — delivered by the HTTP load driver on the
#: wire, never through the frontend's fault_hook seams
NETWORK_FAULT_KINDS = ("client_disconnect", "slow_reader", "conn_reset")


class InjectedFault(ServingError):
    """The exception an injected ``kill_replica`` raises inside the
    pump — a :class:`ServingError` subclass, so handle failure and
    router failover treat it exactly like a real engine death."""


@dataclasses.dataclass(frozen=True)
class FaultSpec:
    """One deterministic fault.

    ``at`` is the trigger index in the fault kind's own counter —
    pump iterations (0-based) for ``kill_replica``/``pump_stall``,
    submissions for ``admission_reject``, the token index for
    ``client_disconnect``/``slow_reader``; ignored by
    ``slow_consumer``/``conn_reset``. ``count`` bounds repeating faults
    (stalled iterations / rejected submissions / affected request ids
    for network kinds); ``delay_ms`` is the stall, per-tick consumer
    delay, or the slow reader's stall duration."""

    kind: str
    replica: int = 0
    at: int = 0
    count: int = 1
    delay_ms: float = 0.0

    def __post_init__(self):
        if self.kind not in FAULT_KINDS:
            raise ValueError(f"unknown fault kind {self.kind!r} "
                             f"(one of {FAULT_KINDS})")
        if self.replica < 0 or self.at < 0:
            raise ValueError("replica and at must be >= 0")
        if self.count < 1:
            raise ValueError("count must be >= 1")
        if self.delay_ms < 0:
            raise ValueError("delay_ms must be >= 0")
        if self.kind in ("pump_stall", "slow_consumer", "slow_reader") \
                and self.delay_ms == 0:
            raise ValueError(f"{self.kind} needs delay_ms > 0")


@dataclasses.dataclass(frozen=True)
class FaultPlan:
    """An ordered bundle of faults for one chaos run."""

    specs: Tuple[FaultSpec, ...] = ()

    def for_replica(self, replica: int) -> Tuple[FaultSpec, ...]:
        return tuple(s for s in self.specs if s.replica == replica)

    def injector(self, replica: int) -> Optional["FaultInjector"]:
        """The replica's frontend hook, or None when this plan holds
        nothing for it (no hook = zero per-iteration overhead).
        Network kinds never ride the hook — they are the load driver's
        to deliver (``net_faults_for``)."""
        specs = tuple(s for s in self.for_replica(replica)
                      if s.kind not in NETWORK_FAULT_KINDS)
        return FaultInjector(specs) if specs else None

    def net_faults_for(self, request_id: int) -> Tuple[FaultSpec, ...]:
        """The network faults the load driver applies to ``request_id``
        — a network spec covers request ids ``{0, …, count-1}``
        (deterministic, so prefix-tolerant checks know their ids)."""
        return tuple(s for s in self.specs
                     if s.kind in NETWORK_FAULT_KINDS
                     and request_id < s.count)

    # -- JSON round-trip (rides inside ScenarioSpec) -------------------------

    def to_json(self) -> str:
        return json.dumps([dataclasses.asdict(s) for s in self.specs],
                          sort_keys=True)

    @classmethod
    def from_json(cls, text: str) -> "FaultPlan":
        return cls(specs=tuple(FaultSpec(**d) for d in json.loads(text)))

    @classmethod
    def random(cls, seed: int, n_replicas: int, *, n_faults: int = 1,
               kinds: Sequence[str] = ("kill_replica",),
               max_at: int = 8, delay_ms: float = 20.0) -> "FaultPlan":
        """Sample a plan from ``default_rng(seed)`` — same seed, same
        faults, byte-identical ``to_json()``."""
        import numpy as np

        if n_replicas < 1:
            raise ValueError("n_replicas must be >= 1")
        rng = np.random.default_rng(seed)
        specs: List[FaultSpec] = []
        for _ in range(n_faults):
            kind = kinds[int(rng.integers(0, len(kinds)))]
            specs.append(FaultSpec(
                kind=kind,
                replica=int(rng.integers(0, n_replicas)),
                at=int(rng.integers(0, max_at + 1)),
                count=int(rng.integers(1, 4)),
                delay_ms=delay_ms if kind in ("pump_stall",
                                              "slow_consumer") else 0.0))
        return cls(specs=tuple(specs))


class FaultInjector:
    """One replica's fault delivery, plugged into
    ``ServingFrontend(fault_hook=...)``.

    Thread-safe: ``on_submit`` runs on submitter threads, ``on_pump``
    on the pump thread, ``consume_delay_s`` on the router's tick —
    the trigger counters share one lock. The sleeps happen OUTSIDE the
    lock (a stall must wedge only its own pump, never a submitter)."""

    def __init__(self, specs: Sequence[FaultSpec]):
        self.specs = tuple(specs)
        self._lock = threading.Lock()
        self._pumps = 0
        self._submits = 0
        self._rejected = 0
        self.fired: List[str] = []       # kinds delivered, in order

    def _record(self, kind: str) -> None:
        self.fired.append(kind)

    # -- frontend seams ------------------------------------------------------

    def on_pump(self, frontend) -> None:
        """Top of every pump iteration: kill (raise) or stall (sleep)."""
        stall_s = 0.0
        kill: Optional[FaultSpec] = None
        with self._lock:
            idx = self._pumps
            self._pumps += 1
            for spec in self.specs:
                if spec.kind == "kill_replica" and idx >= spec.at:
                    kill = spec
                    break
                if spec.kind == "pump_stall" \
                        and spec.at <= idx < spec.at + spec.count:
                    stall_s += spec.delay_ms * 1e-3
                    self._record("pump_stall")
            if kill is not None:
                self._record("kill_replica")
        if kill is not None:
            raise InjectedFault(
                f"replica killed by fault injection at pump "
                f"iteration {idx} (spec at={kill.at})")
        if stall_s:
            time.sleep(stall_s)

    def on_submit(self, frontend, request) -> None:
        """Before a submission lands: reject ``count`` submissions
        starting at the ``at``-th."""
        reject = False
        with self._lock:
            idx = self._submits
            self._submits += 1
            for spec in self.specs:
                if spec.kind == "admission_reject" and idx >= spec.at \
                        and self._rejected < spec.count:
                    self._rejected += 1
                    self._record("admission_reject")
                    reject = True
                    break
        if reject:
            raise ServingError(
                f"submission {idx} rejected by fault injection")

    # -- router seam ---------------------------------------------------------

    def consume_delay_s(self, request_id) -> float:
        """Per-tick token-forwarding delay for ``request_id`` (the
        slow-consumer fault; 0.0 when none is planned)."""
        del request_id
        for spec in self.specs:
            if spec.kind == "slow_consumer":
                return spec.delay_ms * 1e-3
        return 0.0
