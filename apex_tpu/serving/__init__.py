"""The serving subsystem: paged KV cache + continuous-batching decode.

Lock-step ``generate`` allocates one monolithic ``(batch, kv, max_len, d)``
cache per request batch and pads every sequence to the longest — a finished
sequence wastes its slot (and its cache HBM) until the whole batch drains.
This package replaces that with the two serving-stack staples:

- **Paged KV cache** (``kv_pool``): per-layer K/V live in a static
  ``(num_pages, kv, page_size, d)`` pool; each sequence owns
  ``ceil(len/page_size)`` pages named by an int32 block table. Alloc /
  free / defrag are pure-JAX index ops over a fixed-size free stack — no
  shape ever changes, so nothing recompiles at admission or retirement.
  (vLLM / PagedAttention, Kwon et al. 2023.)
- **Continuous batching** (``scheduler``): a fixed-size SLOT array of
  in-flight sequences; at step boundaries finished slots retire (pages
  freed) and queued requests admit into the vacancy — iteration-level
  scheduling (Orca, Yu et al. 2022). The decode step itself is one jitted
  program over the slot array, with per-slot lengths, EOS masks, and
  remaining-token counts carried through a ``lax.scan``.

- **Shared-prefix caching** (``prefix_cache``): a host-side radix tree
  over token ids whose nodes name pool pages already holding that
  prefix's K/V — requests sharing a system prompt / few-shot header skip
  its prefill entirely, sharing the pages read-only under per-page int32
  refcounts with LRU eviction at refcount 0 (RadixAttention, Zheng et
  al. 2023). Opt-in via ``PagedDecodeEngine(..., prefix_cache=True)`` /
  ``generate(..., paged=True, prefix_cache=True)``.

- **Tiered KV pool** (``host_tier``): a byte-budgeted host-RAM LRU
  under the device pool — refcount-0 radix pages evicted under pressure
  DEMOTE (async gather to pinned host memory, raw pool-dtype bytes +
  scales) instead of dropping, and a later hit on a host-resident node
  PROMOTES into freshly popped pages instead of re-prefilling; a
  preemption spill's pages ride the same path, so a resume promotes
  instead of recomputing. Opt-in via ``PagedDecodeEngine(...,
  host_tier_bytes=...)`` (requires ``prefix_cache=True``;
  docs/serving.md "Tiered KV pool").

- **Async front-end** (``frontend`` + ``policy``): streaming ingest
  (``submit()`` returns a per-token :class:`StreamHandle`), a
  priority/deadline admission policy, preemption that spills a victim's
  full pages back through the prefix cache (resumption is a cache hit),
  and a pump that overlaps host-side retirement/admission work with the
  next jitted decode chunk. ``PagedDecodeEngine.run`` is a thin
  closed-loop wrapper over it (docs/frontend.md).

- **Tensor parallelism** (``tp``): ``TensorParallelPagedEngine`` runs
  ONE logical engine over a ``tp``-axis mesh — the pool's K/V shard
  along the kv-head axis (each chip holds ``1/tp`` the pool bytes),
  block tables and scheduling stay replicated/host-side, and every
  engine program runs under ``shard_map`` with the models' Megatron TP
  layers (docs/tp_serving.md).

- **Data-parallel replication** (``router`` + ``faults``): N
  frontend+engine replicas (each optionally TP) behind one
  :class:`ReplicaRouter` — queue-depth load balancing, rendezvous-hash
  prefix-affinity routing, overload shedding with retry-after,
  graceful drain, and supervised failure recovery (a dead replica's
  in-flight requests resume on survivors with their generated tokens
  folded into the prompt; exhausted recovery fails handles with a
  terminal :class:`ServingError`, never a hang). ``faults`` makes the
  failures seeded, replayable scenario inputs (docs/router.md).

- **HTTP/SSE surface** (``http`` + ``aio``): a stdlib-asyncio server
  exposing ``POST /v1/generate`` token streaming (plus health, metrics,
  and cost endpoints on the same port) over :class:`AsyncStreamHandle`,
  an awaitable adapter on the thread-based pump. Admission ties to the
  frontend's ``backpressure_window`` — a stalled reader spills its slot
  through the preemption path instead of pinning pages for a socket —
  and a client disconnect cancels at the next sync boundary and frees
  everything. :class:`HttpReplicaClient` wraps a remote server in the
  frontend surface so a :class:`ReplicaRouter` can supervise N
  networked replicas exactly like in-process ones (docs/http.md).

The decode attention is ``apex_tpu.ops.paged_attention`` — a Pallas kernel
that gathers pages via the block table with scalar-prefetch index maps.
"""

from apex_tpu.serving.aio import AsyncStreamHandle  # noqa: F401
from apex_tpu.serving.faults import (  # noqa: F401
    NETWORK_FAULT_KINDS,
    FaultInjector,
    FaultPlan,
    FaultSpec,
    InjectedFault,
)
from apex_tpu.serving.http import (  # noqa: F401
    HttpReplicaClient,
    HttpServingServer,
)
from apex_tpu.serving.frontend import (  # noqa: F401
    ServingError,
    ServingFrontend,
    StreamHandle,
)
from apex_tpu.serving.host_tier import HostPageTier  # noqa: F401
from apex_tpu.serving.kv_pool import (  # noqa: F401
    alloc_slot,
    alloc_slot_shared,
    defrag,
    defrag_map,
    drop_slot_pages,
    evict_pages,
    free_page_count,
    free_slot,
    init_paged_cache,
    pages_for,
    prefill_into_pages,
    release_slot,
)
from apex_tpu.serving.policy import PriorityDeadlinePolicy  # noqa: F401
from apex_tpu.serving.router import (  # noqa: F401
    OverloadError,
    ReplicaRouter,
    RouterHandle,
    RouterPolicy,
)
from apex_tpu.serving.prefix_cache import PrefixCache  # noqa: F401
from apex_tpu.serving.scheduler import (  # noqa: F401
    PagedDecodeEngine,
    Request,
    generate_paged,
    make_shared_admit,
)
from apex_tpu.serving.tp import (  # noqa: F401
    TensorParallelPagedEngine,
    shard_model_variables,
    tp_mesh,
)
