"""Continuous-batching decode engine over the paged KV pool.

Iteration-level scheduling (Orca, Yu et al. 2022): a fixed array of
``num_slots`` decode slots advances one token per engine step in a SINGLE
jitted program; at step boundaries the host retires finished slots (EOS or
token budget — their pages return to the free stack immediately) and
admits queued requests into the vacancies. Short requests therefore never
pad to the batch's longest, and a drained slot is re-filled instead of
idling until the batch ends — the two wastes of lock-step ``generate``.

Static shapes throughout: admission PREFILLS through the models' existing
contiguous flash path at a page-size-rounded prompt bucket (one compile
per bucket, reused forever), scatters the resulting K/V into the slot's
pages, and the decode step is one program at one shape. Inside the step
scan the carry holds per-slot (token, EOS-done mask, remaining-token
count) — a finished slot keeps emitting EOS at its frozen state until the
host syncs, exactly like ``decode_loop``'s EOS rows, so ``sync_every > 1``
trades host syncs for (bounded) post-finish padding steps.

Sampling reuses ``models/generation``'s helpers. Greedy decode is
token-identical to per-request lock-step ``generate``; sampled decode
derives each request's key stream from ``fold_in(rng, request_index)`` so
outputs are SCHEDULING-INVARIANT (they depend on the request and the key,
not on which slot or step the request landed in — stronger than lock-step,
whose draws change with batch composition).

The host scheduling loop itself lives in ``serving/frontend.py``
(:class:`~apex_tpu.serving.frontend.ServingFrontend`): streaming ingest,
priority/deadline admission (``serving/policy.py``), page-spilling
preemption, and a pump that overlaps host-side retirement/admission work
with the next jitted decode chunk. :meth:`PagedDecodeEngine.run` is a
thin closed-loop wrapper over that frontend — this module owns the
engine STATE (pool, prefix cache, compiled admit/step programs,
observability identity) the frontend drives.

Every program is compiled through two overridable seams —
``_make_cache`` (pool allocation) and ``_compile`` (role-tagged jit) —
which is how ``serving/tp.py``'s
:class:`~apex_tpu.serving.tp.TensorParallelPagedEngine` runs the SAME
scheduler over a tensor-parallel mesh: head-sharded pool, shard_mapped
programs, replicated scheduling state (docs/tp_serving.md).

``prefix_cache=True`` adds cross-request KV reuse (RadixAttention, Zheng
et al. 2023; ``serving/prefix_cache.py``): admission walks a radix tree
of cached full pages, points the slot's block table at the matched pages
(refcounted, read-only) and prefills only the uncached tail through
``make_shared_admit``; retirement moves the request's full-page prefix
into the tree instead of the free stack, and the stack is replenished by
LRU eviction of refcount-0 cached pages on demand. Greedy outputs stay
token-identical to the cache-off engine: the shared pages replay
bitwise-stored K/V, never re-derived. (The re-prefilled TAIL of a hit
rides dense cached attention where the cold path rides the flash kernel
— exact in fp32; under bf16 the two summation orders can differ in low
bits, so a near-tied argmax could flip, the same caveat as
``speculative_generate``'s chunked-verify exactness note.)
"""

from __future__ import annotations

import dataclasses
import functools
import itertools
from typing import Any, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from apex_tpu.mesh import MODEL_AXIS
from apex_tpu.models.generation import (_greedy_token, _sample_token,
                                        init_cache, validate_sampling)
from apex_tpu.obs.events import EventLog
from apex_tpu.obs.spans import SpanTracer
from apex_tpu.ops._dispatch import round_up
from apex_tpu.ops.quant import resolve_kv_dtype
from apex_tpu.serving import kv_pool
from apex_tpu.serving.host_tier import HostPageTier
from apex_tpu.serving.prefix_cache import PrefixCache

#: run() counters in the instrument registry (``serving.<name>``); the
#: per-run stats dict is the DELTA of these across the run — the registry
#: is the state of record, the dict a derived view
_RUN_COUNTERS = ("admitted", "retired", "decode_steps", "busy_slot_steps",
                 "prefix_hits", "prefill_tokens_total",
                 "prefill_tokens_computed", "evicted_pages",
                 "deferred_admissions", "defrag_runs",
                 "preemptions", "resumes", "backpressure_spills",
                 "deadline_misses",
                 "tpot_slo_misses", "window_dropped_pages",
                 "spec_rounds", "spec_tokens", "chunked_prefills",
                 "prefill_chunks")

#: per-request latency histograms (``serving.<name>``, log-bucketed ms)
_RUN_HISTOGRAMS = ("ttft_ms", "tpot_ms", "queue_wait_ms", "decode_step_ms")

#: per-process engine ids, the ``engine`` label on run counters
_ENGINE_IDS = itertools.count()


@dataclasses.dataclass
class Request:
    """One decode request: a 1-D int32 prompt and its token budget, plus
    the serving front-end's optional scheduling fields (defaults keep
    every pre-frontend call site constructing unchanged).

    - ``priority``: scheduling class, larger int = more important. The
      front-end serves the pending queue highest-priority first and may
      preempt a strictly-lower-priority RUNNING request for a blocked
      higher-priority one (``serving/policy.py``). 0 (the default) is
      plain FIFO traffic.
    - ``deadline_ms``: a TTFT service-level objective — the request
      should see its first token within ``deadline_ms`` of its arrival.
      Breaks ties inside a priority class (earliest deadline first) and
      arms preemption when the request would otherwise sit blocked past
      it. Missing the deadline never drops the request; misses are
      counted (``serving.deadline_misses``). None = no SLO.
    - ``arrival_time``: when the request entered the system, in the
      monotonic ``time.perf_counter`` timebase (NOT wall clock) so
      deadlines survive clock steps. None = stamped at ``submit()``;
      trace replays pass explicit values.
    - ``tpot_slo_ms``: a steady-state time-per-output-token SLO. Checked
      once at retirement against the request's lifecycle TPOT
      (docs/observability.md); a miss increments
      ``serving.tpot_slo_misses`` and feeds the rolling
      ``serving.slo_burn`` gauge — the request is never truncated.
      None = no TPOT SLO.
    - ``trace_id``: process-independent trace identity (32 lowercase hex
      chars, the ``traceparent`` trace-id field). Minted router-side for
      routed requests (``obs/fleet.py``), carried over HTTP as a
      ``traceparent`` header, and tagged onto the tracer's ``enqueue``
      span on every replica that ever holds the request — the key
      ``stitch_traces()`` merges failed-over span fragments on. None =
      untraced (single-process callers lose nothing).
    """

    prompt: Any                      # (s0,) int array
    max_new_tokens: int
    priority: int = 0
    deadline_ms: Optional[float] = None
    arrival_time: Optional[float] = None
    tpot_slo_ms: Optional[float] = None
    trace_id: Optional[str] = None


def _donate_cache():
    # buffer donation keeps the page pool in place across step/admit calls
    # on TPU; the CPU backend has no donation and would warn every call
    return (0,) if jax.default_backend() == "tpu" else ()


def prompt_bucket(s0: int, page_size: int, max_positions: int) -> int:
    """The admission compile-key bucket for a raw prompt length: pad up
    to a whole page (capped at the position table) so one program serves
    every length in the page — the compile-count contract the IR tier's
    ``gpt2s_engine_admit_bucketed`` case traces at two same-bucket
    lengths (``ir-compile-key-cardinality``). Admission and the lint
    harness MUST share this function: the contract is only binding on
    the engine if the engine's own bucketing is what gets traced."""
    return min(round_up(max(s0, 1), page_size), max_positions)


def _bucket_match_pages(m: int) -> int:
    """Round a radix match depth DOWN to a power of two pages. Retirement
    inserts prompts AND generated tokens, so raw match depths take many
    distinct values — and every distinct ``t_start`` is a fresh
    shared-admit XLA compile stalling the admission loop. The power-of-two
    floor bounds the compile-key set at ``log2(max_pages)`` per tail
    bucket, at the cost of re-prefilling at most half the matched pages
    (none at all for power-of-two-page shared headers, the common case)."""
    return 1 << (m.bit_length() - 1) if m > 0 else 0


def make_shared_admit(model, *, t_start: int, tail_bucket: int,
                      first_token=None, axis_name: str = MODEL_AXIS):
    """Build the shared-prefix admission program (one compile per
    ``(t_start, tail_bucket)`` pair, cached by the engine; also the
    ``tpu_aot.py`` sweep's prefix-cached decode case).

    The matched prefix (``t_start`` tokens = ``t_start/page_size`` whole
    cached pages) is GATHERED from the pool into a contiguous buffer, and
    the model forward runs over ONLY the ``tail_bucket``-padded uncached
    tail with the buffer as its KV cache at static length ``t_start`` —
    the tail attends over the shared prefix through the models' existing
    cached path, but the prefix contributes zero forward FLOPs. The tail's
    K/V then scatters into the slot's private pages
    (``prefill_into_pages(start=t_start)`` — shared pages are never
    written: copy-on-write at page granularity, the partially-filled
    boundary page is always private) and the first token samples from the
    prompt-final logits.

    Returns ``admit(cache, variables, tail_ids, s0, slot, shared_row,
    n_private, req_key, samp0=0) -> (cache, tok0)`` where ``shared_row``
    is a ``(max_pages,)`` int32 row whose first ``t_start/page_size``
    entries are the matched physical pages and ``samp0`` is the sampled
    first token's index in the request's key stream (nonzero only for a
    preemption resume, which continues the stream where the preempted
    segment stopped — scheduling invariance holds across preemption)."""
    cfg = model.config
    if t_start < 1 or tail_bucket < 1:
        raise ValueError("shared admission needs t_start >= 1 matched "
                         "tokens and tail_bucket >= 1 tail tokens")
    if first_token is None:
        def first_token(last, _key, _samp0=0):
            return _greedy_token(last, axis_name)
    bucket = t_start + tail_bucket

    def admit(cache, variables, tail_ids, s0, slot, shared_row, n_private,
              req_key, samp0=0):
        ps = kv_pool.page_size_of(cache)
        if t_start % ps:
            raise ValueError(f"t_start={t_start} must be a page multiple "
                             f"({ps})")
        m = t_start // ps
        contig = init_cache(cfg, 1, bucket)
        layers = []
        for pool_lc, lc in zip(cache["layers"], contig["layers"]):
            def gathered(pages, dst, scales=None):
                # (m, kv, ps, d) page tiles -> the buffer's leading
                # t_start positions; a quantized pool dequantizes by its
                # gathered per-(page, kv_head) scales on the way out
                kv, d = pages.shape[1], pages.shape[3]
                if scales is not None:
                    pages = pages.astype(jnp.float32) * \
                        scales[:, :, None, None]
                block = pages.transpose(1, 0, 2, 3).reshape(
                    1, kv, t_start, d)
                return dst.at[:, :, :t_start, :].set(
                    block.astype(dst.dtype))
            quantized = "k_scales" in pool_lc
            layers.append(
                {"k": gathered(pool_lc["k_pages"][shared_row[:m]], lc["k"],
                               pool_lc["k_scales"][shared_row[:m]]
                               if quantized else None),
                 "v": gathered(pool_lc["v_pages"][shared_row[:m]], lc["v"],
                               pool_lc["v_scales"][shared_row[:m]]
                               if quantized else None)})
        # static len t_start: the tail chunk is a chunked continuation —
        # bounds check at trace time, dense cached attention over the
        # buffer (the flash path needs len 0, which the prefix occupies)
        contig = {"layers": layers, "len": t_start}
        logits, contig = model.apply(variables, tail_ids, cache=contig)
        last = lax.dynamic_slice_in_dim(logits, s0 - t_start - 1, 1,
                                        axis=1)[:, 0]
        cache = kv_pool.alloc_slot_shared(cache, slot, shared_row, m,
                                          n_private)
        cache = kv_pool.prefill_into_pages(cache, slot, contig["layers"],
                                           s0, start=t_start)
        tok0 = first_token(last, req_key, samp0)[0]
        return cache, tok0

    return admit


def make_prefill_chunk(model, *, chunk: int, first_token=None,
                       axis_name: str = MODEL_AXIS):
    """Build the chunked-prefill step program (one compile per engine;
    also the ``tpu_aot.py`` sweep's chunked-prefill case).

    One call pushes the next ``chunk`` prompt tokens of ONE slot through
    the model's PAGED s>1 path: a slot view (the shared pools plus the
    slot's own block-table row and length) rides ``model.apply`` exactly
    like a decode step, so the chunk's K/V lands directly in the slot's
    pages — no contiguous staging buffer, no scatter — and the per-query
    causal band (``len - s + i``) keeps position ``i`` from seeing
    positions beyond itself inside the chunk. The final chunk of a
    prompt is zero-padded to ``chunk`` tokens; padding rows write
    garbage K/V at positions >= the true length, which the length
    update below never exposes (the causal band reads strictly below
    ``len``, and the next chunk or first decode step overwrites them).

    Returns ``prefill_step(cache, variables, ids, slot, valid, req_key,
    samp0) -> (cache, tok0)``: ``ids`` is ``(1, chunk)``, ``valid`` the
    chunk's true token count, and ``tok0`` the first-token sample off
    logit ``valid - 1`` — meaningful only on the prompt's final chunk
    (earlier chunks' tok0 is discarded by the frontend)."""
    if chunk < 1:
        raise ValueError("prefill chunk must be >= 1 token")
    if first_token is None:
        def first_token(last, _key, _samp0=0):
            return _greedy_token(last, axis_name)

    def prefill_step(cache, variables, ids, slot, valid, req_key, samp0):
        view = {
            "layers": cache["layers"],
            "block_tables": lax.dynamic_slice_in_dim(
                cache["block_tables"], slot, 1, axis=0),
            "len": lax.dynamic_slice_in_dim(cache["len"], slot, 1, axis=0),
        }
        logits, view = model.apply(variables, ids, cache=view)
        # advance by the TRUE token count, not the padded chunk width:
        # padded positions stay above len and are never read
        cache = dict(cache, layers=view["layers"],
                     len=cache["len"].at[slot].add(valid))
        last = lax.dynamic_slice_in_dim(logits, valid - 1, 1, axis=1)[:, 0]
        tok0 = first_token(last, req_key, samp0)[0]
        return cache, tok0

    return prefill_step


class PagedDecodeEngine:
    """Continuous-batching greedy/sampled decode over ``num_slots`` slots.

    ``run(requests)`` processes the whole queue and returns
    ``(outputs, stats)`` where ``outputs[i]`` is request ``i``'s generated
    tokens (up to and including its first EOS) and ``stats`` counts engine
    decode steps — the serving cost driver lock-step padding inflates.
    """

    def __init__(self, model, variables, *, num_slots: int,
                 page_size: int = 16, num_pages: Optional[int] = None,
                 max_pages_per_seq: Optional[int] = None,
                 eos_token_id: Optional[int] = None,
                 temperature: float = 0.0, top_k: Optional[int] = None,
                 top_p: Optional[float] = None, rng=None,
                 sync_every: int = 1, axis_name: str = MODEL_AXIS,
                 prefix_cache: bool = False,
                 draft_model=None, draft_variables=None, draft_len: int = 0,
                 prefill_chunk: Optional[int] = None, kv_dtype=None,
                 draft_kv_dtype="match",
                 host_tier_bytes: Optional[int] = None):
        cfg = model.config
        if num_slots < 1:
            raise ValueError("num_slots must be >= 1")
        if sync_every < 1:
            raise ValueError("sync_every must be >= 1")
        # quantized KV pages (docs/serving.md "Quantized KV pages"):
        # resolve eagerly so an unsupported kv_dtype raises a NAMED
        # ValueError here — never a silent full-precision fallback
        resolve_kv_dtype(kv_dtype)
        self.kv_dtype = kv_dtype
        # the draft pool mirrors the target pool page-for-page AND
        # dtype-for-dtype: one capacity/cost story covers both pools, so
        # a divergent draft dtype is a named config error, not a knob
        if draft_kv_dtype == "match":
            draft_kv_dtype = kv_dtype
        if draft_len > 0 and draft_kv_dtype != kv_dtype:
            raise ValueError(
                f"kv-dtype-mismatch: the speculative draft pool must "
                f"share the target pool's kv_dtype (target "
                f"{kv_dtype!r}, draft {draft_kv_dtype!r}) — the pools "
                f"mirror each other slot-for-slot and page-for-page")
        self.model = model
        self.variables = variables
        self.cfg = cfg
        self.num_slots = num_slots
        self.page_size = page_size
        self.eos_token_id = eos_token_id
        self.temperature = temperature
        self.top_k = top_k
        self.top_p = top_p
        self.rng = validate_sampling(temperature, top_k, top_p, rng)
        self.sync_every = sync_every
        self.axis_name = axis_name
        # sliding-window models: the paged kernel bands attention to the
        # window and the frontend drops pages below the band at sync
        # boundaries (kv_pool.drop_slot_pages) — O(window) live pages per
        # slot. Dropped pages cannot double as shared cache property, so
        # the window and the radix prefix cache are mutually exclusive.
        # CONTRACT: a config EXPOSING ``sliding_window`` promises its
        # model's paged branch passes ``window=`` to ``paged_attention``
        # (LlamaConfig does; GPTConfig has no such field) — the drop
        # below frees pages the band can no longer read, so an unbanded
        # paged path under this attribute would read freed null pages.
        self.window = getattr(cfg, "sliding_window", None)
        if prefix_cache and self.window is not None:
            raise ValueError(
                "prefix_cache does not compose with sliding-window "
                "models: the engine drops a windowed slot's pages once "
                "they fall below the attention band, and a dropped page "
                "cannot be shared radix-cache property (decode windowed "
                "models with prefix_cache=False)")
        # in-engine speculative decode (docs/serving.md): every engine
        # step drafts ``draft_len`` tokens per slot through a small draft
        # model's own paged pool and verifies the block in ONE
        # s = draft_len + 1 paged target step — the s>1 kernel
        # generalization is what makes the verify a single program at one
        # shape. Acceptance is per-slot (continuous batching never stalls
        # a slot on its neighbours' rejections, unlike lock-step
        # ``speculative_generate``'s min-over-batch).
        self.draft_model = draft_model
        self.draft_variables = draft_variables
        self.draft_len = draft_len
        self.prefill_chunk = prefill_chunk
        if draft_len < 0:
            raise ValueError("draft_len must be >= 0")
        if draft_len > 0:
            if draft_model is None:
                raise ValueError(
                    "draft_len > 0 needs a draft_model (and its "
                    "draft_variables) to propose tokens")
            if temperature:
                raise ValueError(
                    "in-engine speculative decode is greedy-only: "
                    "acceptance compares draft proposals against the "
                    "target's greedy predictions (set temperature=0)")
            if prefix_cache:
                raise ValueError(
                    "speculative decode does not compose with "
                    "prefix_cache yet: shared pages would need a second "
                    "refcounted draft-pool mirror (run one or the other)")
            if self.window is not None or getattr(
                    draft_model.config, "sliding_window", None) is not None:
                raise ValueError(
                    "speculative decode does not support sliding-window "
                    "models: the frontend drops pages below the band, "
                    "and the draft pool would need the same banded drop "
                    "protocol (use a full-attention target and draft)")
            if prefill_chunk is not None:
                raise ValueError(
                    "speculative decode and chunked prefill are mutually "
                    "exclusive engine modes for now (pick one)")
            if draft_len + 1 > page_size:
                raise ValueError(
                    f"draft_len + 1 = {draft_len + 1} exceeds the paged "
                    f"kernel's query-block limit page_size={page_size}")
        # chunked prefill (Sarathi-style): admission feeds long prompts
        # through the PAGED path in fixed ``prefill_chunk``-token pieces
        # interleaved with decode chunks, so a long prompt never
        # monopolizes the device between two decode steps (TTFT tail)
        if prefill_chunk is not None:
            if not 1 <= prefill_chunk <= page_size:
                raise ValueError(
                    f"prefill_chunk must be in 1..page_size ({page_size}), "
                    f"got {prefill_chunk}: chunks ride the paged kernel's "
                    f"query block, which is capped at one page")
            if self.window is not None:
                raise ValueError(
                    "chunked prefill does not support sliding-window "
                    "models yet: in-progress chunks hold positions the "
                    "window-page dropper would free mid-prefill (use "
                    "monolithic admission for windowed models)")
        if max_pages_per_seq is None:
            max_pages_per_seq = kv_pool.cdiv(cfg.max_position_embeddings,
                                             page_size)
        if num_pages is None:
            # worst case: every slot holds a max-length sequence (+ null)
            num_pages = 1 + num_slots * max_pages_per_seq
        self.cache = self._make_cache(num_slots, num_pages, page_size,
                                      max_pages_per_seq)
        # the draft pool mirrors the target pool's geometry slot-for-slot
        # and page-for-page: one allocation decision covers both
        self.draft_cache = (self._make_cache(num_slots, num_pages,
                                             page_size, max_pages_per_seq,
                                             config=draft_model.config)
                            if draft_len > 0 else None)
        # observability (docs/observability.md): a bounded postmortem
        # event ring for the engine's lifetime, and the last run's span
        # tracer (fresh per run; run(tracer=...) injects one). Every
        # serving/pool/prefix instrument carries this ``engine`` label
        # so concurrent engines in one process never mix each other's
        # increments, distributions, or pool-health levels
        self.events = EventLog(capacity=4096)
        self.tracer: Optional[SpanTracer] = None
        self.obs_labels = {"engine": str(next(_ENGINE_IDS))}
        # cross-request KV reuse: the host radix tree naming cached pages
        self.prefix = (PrefixCache(page_size,
                                   metrics_labels=self.obs_labels)
                       if prefix_cache else None)
        # tiered pool (docs/serving.md "Tiered KV pool"): a host-RAM
        # byte-budgeted LRU under the device pool — evicted radix pages
        # demote (gather -> host) instead of dropping, and a later hit
        # promotes into fresh pages instead of re-prefilling. Keyed by
        # radix-node identity, so it REQUIRES the prefix cache: without
        # the tree there is no name to file a demoted page under.
        if host_tier_bytes is not None and host_tier_bytes > 0:
            if self.prefix is None:
                raise ValueError(
                    "host_tier_bytes requires prefix_cache=True: the "
                    "tier files demoted pages under radix-node identity "
                    "(their token path), which only the prefix cache "
                    "names")
            self.host_tier = HostPageTier(host_tier_bytes,
                                          page_size=page_size,
                                          metrics_labels=self.obs_labels)
        else:
            self.host_tier = None
        self._admit_jit = {}             # prompt bucket -> compiled admit
        self._shared_admit_jit = {}      # (t_start, tail_bucket) -> admit
        self._spec_admit_jit = {}        # prompt bucket -> spec admit
        self._step_jit = None
        self._spec_step_jit = None
        self._chunk_jit = None
        donate = _donate_cache()
        self._free_jit = self._compile(
            kv_pool.free_slot, ("cache", "rep"), ("cache",), donate)
        self._release_jit = self._compile(
            kv_pool.release_slot, ("cache", "rep", "rep"), ("cache",),
            donate)
        self._evict_jit = self._compile(
            kv_pool.evict_pages, ("cache", "rep", "rep"), ("cache",),
            donate)
        self._defrag_jit = self._compile(
            kv_pool.defrag_map, ("cache", "rep"), ("cache", "rep"), donate)
        self._drop_jit = self._compile(
            kv_pool.drop_slot_pages, ("cache", "rep", "rep"), ("cache",),
            donate)
        if self.host_tier is not None:
            # the tiered pool's two device programs, each ONE compile:
            # demote depth and promote depth are DATA (a null-padded
            # HOST_COPY_CHUNK page row + a traced count), never a compile
            # key. The gather is a pure READ — donating the cache to it
            # would free the pool out from under the engine.
            self._gather_jit = self._compile(
                kv_pool.gather_pages, ("cache", "rep"), ("tiles",))
            self._promote_jit = self._compile(
                kv_pool.promote_pages, ("cache", "rep", "rep", "tiles"),
                ("cache",), donate)
        if draft_len > 0:
            # draft-pool mirrors of the maintenance programs, compiled
            # through the same seam under the draft roles so TP shards
            # them with the DRAFT config's head count
            self._draft_free_jit = self._compile(
                kv_pool.free_slot, ("draft_cache", "rep"),
                ("draft_cache",), donate)
            self._draft_defrag_jit = self._compile(
                kv_pool.defrag_map, ("draft_cache", "rep"),
                ("draft_cache", "rep"), donate)
        if prefill_chunk is not None:
            # chunked admission allocates the slot's pages up front (the
            # whole-prompt page need is known) but starts at len 0 —
            # chunks advance len as they land; alloc_slot itself never
            # touches len, so set it explicitly on both variants
            def chunk_alloc(cache, slot, n_pages):
                cache = kv_pool.alloc_slot(cache, slot, n_pages)
                return dict(cache, len=cache["len"].at[slot].set(0))

            def chunk_alloc_shared(cache, slot, shared_row, n_shared,
                                   n_private):
                ps = kv_pool.page_size_of(cache)
                cache = kv_pool.alloc_slot_shared(cache, slot, shared_row,
                                                  n_shared, n_private)
                return dict(cache, len=cache["len"].at[slot].set(
                    n_shared * ps))

            self._chunk_alloc_jit = self._compile(
                chunk_alloc, ("cache", "rep", "rep"), ("cache",), donate)
            self._chunk_alloc_shared_jit = self._compile(
                chunk_alloc_shared, ("cache",) + ("rep",) * 4, ("cache",),
                donate)

    # --- compilation seams (overridden by serving/tp.py) --------------------

    def _make_cache(self, num_slots, num_pages, page_size,
                    max_pages_per_seq, config=None):
        """Allocate a paged cache for ``config`` (default: the target
        model's — speculative engines call this a second time with the
        draft model's config for the draft pool). The single-chip engine
        holds the whole pool on the default device;
        :class:`~apex_tpu.serving.tp.TensorParallelPagedEngine`
        overrides this to allocate one GLOBAL pool whose K/V head axis
        is sharded over its ``tp`` mesh."""
        return kv_pool.init_paged_cache(
            config if config is not None else self.cfg, num_slots,
            num_pages=num_pages, page_size=page_size,
            max_pages_per_seq=max_pages_per_seq, kv_dtype=self.kv_dtype)

    def _compile(self, fn, in_roles, out_roles, donate=()):
        """The single seam every engine program is compiled through.

        ``in_roles`` / ``out_roles`` name each positional argument /
        result of ``fn``: ``"cache"`` (the paged pool pytree),
        ``"vars"`` (the model variables), ``"draft_cache"`` /
        ``"draft_vars"`` (the speculative draft model's pool and
        variables), ``"rep"`` (a replicated host-side value — tokens,
        slot indices, masks, keys). The
        single-chip engine ignores the roles and plain-jits;
        :class:`~apex_tpu.serving.tp.TensorParallelPagedEngine` wraps
        ``fn`` in ``shard_map`` over its mesh with per-role
        PartitionSpecs, so every program — pool maintenance included —
        runs SPMD over the same sharded state."""
        del in_roles, out_roles
        return jax.jit(fn, donate_argnums=donate)

    # --- request-key sampling (scheduling-invariant streams) ----------------

    def _first_token(self, last_logits, req_key, samp0=0):
        # ``samp0``: the token's index in the request's fold_in key
        # stream — 0 at a cold admission, the resume point after a
        # preemption (so preempted/resumed sampled decode draws the SAME
        # stream as an uninterrupted run: scheduling invariance)
        if not self.temperature:
            return _greedy_token(last_logits, self.axis_name)
        return _sample_token(last_logits,
                             jax.random.fold_in(req_key, samp0),
                             temperature=self.temperature, top_k=self.top_k,
                             top_p=self.top_p, axis_name=self.axis_name)

    # --- compiled programs --------------------------------------------------

    def _admit_fn(self, bucket: int):
        """Compile (once per prompt bucket): contiguous flash prefill at
        ``bucket`` tokens, page alloc + scatter, first-token sample."""
        if bucket in self._admit_jit:
            return self._admit_jit[bucket]
        model = self.model                       # static via closure

        def admit(cache, variables, ids, s0, slot, n_pages, req_key,
                  samp0=0):
            contig = init_cache(self.cfg, 1, bucket)
            logits, contig = model.apply(variables, ids, cache=contig)
            last = lax.dynamic_slice_in_dim(logits, s0 - 1, 1, axis=1)[:, 0]
            cache = kv_pool.alloc_slot(cache, slot, n_pages)
            cache = kv_pool.prefill_into_pages(cache, slot,
                                               contig["layers"], s0)
            tok0 = self._first_token(last, req_key, samp0)[0]
            return cache, tok0

        fn = self._compile(admit, ("cache", "vars") + ("rep",) * 6,
                           ("cache", "rep"), _donate_cache())
        self._admit_jit[bucket] = fn
        return fn

    def _admit_shared_fn(self, t_start: int, tail_bucket: int):
        """Compile (once per ``(t_start, tail_bucket)``): the shared-prefix
        admission — gather matched pages, tail-only prefill, page-pool
        scatter, first-token sample (``make_shared_admit``)."""
        key = (t_start, tail_bucket)
        if key not in self._shared_admit_jit:
            fn = make_shared_admit(self.model, t_start=t_start,
                                   tail_bucket=tail_bucket,
                                   first_token=self._first_token,
                                   axis_name=self.axis_name)
            self._shared_admit_jit[key] = self._compile(
                fn, ("cache", "vars") + ("rep",) * 7, ("cache", "rep"),
                _donate_cache())
        return self._shared_admit_jit[key]

    def _prefill_chunk_fn(self):
        """Compile (once): one ``prefill_chunk``-token chunk of one
        slot's prompt through the paged s>1 path
        (``make_prefill_chunk``)."""
        if self._chunk_jit is None:
            fn = make_prefill_chunk(self.model, chunk=self.prefill_chunk,
                                    first_token=self._first_token,
                                    axis_name=self.axis_name)
            self._chunk_jit = self._compile(
                fn, ("cache", "vars") + ("rep",) * 5, ("cache", "rep"),
                _donate_cache())
        return self._chunk_jit

    def _spec_admit_fn(self, bucket: int):
        """Compile (once per prompt bucket): the speculative twin of
        ``_admit_fn`` — the same contiguous target prefill + scatter,
        plus the SAME prompt prefilled through the draft model into the
        draft pool (both pools share the slot's page indices, so one
        alloc decision covers both). tok0 comes from the TARGET: the
        emitted stream is always target-greedy."""
        if bucket in self._spec_admit_jit:
            return self._spec_admit_jit[bucket]
        model, draft = self.model, self.draft_model

        def admit(cache, dcache, variables, dvariables, ids, s0, slot,
                  n_pages, req_key, samp0=0):
            contig = init_cache(self.cfg, 1, bucket)
            logits, contig = model.apply(variables, ids, cache=contig)
            last = lax.dynamic_slice_in_dim(logits, s0 - 1, 1, axis=1)[:, 0]
            cache = kv_pool.alloc_slot(cache, slot, n_pages)
            cache = kv_pool.prefill_into_pages(cache, slot,
                                               contig["layers"], s0)
            contig_d = init_cache(draft.config, 1, bucket)
            _, contig_d = draft.apply(dvariables, ids, cache=contig_d)
            dcache = kv_pool.alloc_slot(dcache, slot, n_pages)
            dcache = kv_pool.prefill_into_pages(dcache, slot,
                                                contig_d["layers"], s0)
            tok0 = self._first_token(last, req_key, samp0)[0]
            return cache, dcache, tok0

        donate = (0, 1) if jax.default_backend() == "tpu" else ()
        fn = self._compile(
            admit, ("cache", "draft_cache", "vars", "draft_vars")
            + ("rep",) * 6, ("cache", "draft_cache", "rep"), donate)
        self._spec_admit_jit[bucket] = fn
        return fn

    # --- pool maintenance ---------------------------------------------------

    def _leak_suspected(self, free: int, active) -> bool:
        """True when host liveness accounting says more pages should be
        free than the stack shows — a free miscount somewhere; ``defrag``
        rebuilds the stack from actual liveness and recovers them.
        ``active``: the frontend's slot -> entry map (entries expose
        ``n_private``, the pages the slot owns)."""
        owned = sum(rec.n_private for rec in active.values())
        cached = len(self.prefix) if self.prefix is not None else 0
        usable = kv_pool.num_pages_of(self.cache) - 1    # null page
        return usable - owned - cached > free

    def _defrag_now(self):
        """Run ``defrag_map`` with the prefix cache's resident pages as
        extra liveness (they appear in no block table but must survive),
        then remap the radix tree through the returned page permutation."""
        num_pages = kv_pool.num_pages_of(self.cache)
        extra = np.zeros((num_pages,), bool)
        if self.prefix is not None:
            extra[self.prefix.pages()] = True
        self.cache, new_idx = self._defrag_jit(self.cache,
                                               jnp.asarray(extra))
        if self.prefix is not None:
            self.prefix.remap(np.asarray(new_idx))
        if self.draft_len:
            # the draft pool's alloc/free mirrors the target pool's
            # call-for-call, so it fragments identically — compact it in
            # the same maintenance pass (no prefix pages to pin: the
            # spec engine refuses prefix_cache)
            self.draft_cache, _ = self._draft_defrag_jit(
                self.draft_cache,
                jnp.asarray(np.zeros((num_pages,), bool)))

    def _step_fn(self):
        """Compile (once): ``sync_every`` decode steps as a ``lax.scan``
        whose carry holds the paged cache and per-slot (token, done mask,
        remaining-token count)."""
        if self._step_jit is not None:
            return self._step_jit
        model = self.model
        eos = self.eos_token_id

        def one_step(variables, carry, _):
            cache, tok, done, n_left, req_keys, samp_i = carry
            len_before = cache["len"]
            logits, cache = model.apply(variables, tok[:, None], cache=cache)
            # freeze done/idle slots' lengths: their forward ran (static
            # shapes) against the null-page sink, but their position must
            # not creep — unbounded growth would walk the position table
            # and scale null-page attention work with idle time
            cache = dict(cache, len=jnp.where(done, len_before,
                                              cache["len"]))
            last = logits[:, 0]
            if not self.temperature:
                nxt = _greedy_token(last, self.axis_name)
            else:
                # key = fold_in(request key, the request's OWN token
                # index) -> draws are scheduling-invariant (independent of
                # slot, step, and batch composition)
                keys = jax.vmap(jax.random.fold_in)(req_keys, samp_i)
                nxt = jax.vmap(
                    lambda lg, k: _sample_token(
                        lg[None], k, temperature=self.temperature,
                        top_k=self.top_k, top_p=self.top_p,
                        axis_name=self.axis_name)[0])(last, keys)
            fill = jnp.int32(eos if eos is not None else 0)
            nxt = jnp.where(done, fill, nxt)
            n_left = jnp.where(done, n_left, n_left - 1)
            samp_i = samp_i + 1
            if eos is not None:
                done = jnp.logical_or(done, nxt == eos)
            done = jnp.logical_or(done, n_left <= 0)
            return (cache, nxt, done, n_left, req_keys, samp_i), nxt

        def step(cache, variables, tok, done, n_left, req_keys, samp_i):
            # greedy mode never reads req_keys; the carry layout stays
            # identical across greedy/sampled so both share one step
            # tpu-lint: disable=ir-dead-scan-carry -- (slots, 2) u32/step
            (cache, tok, done, n_left, _, samp_i), toks = lax.scan(
                functools.partial(one_step, variables),
                (cache, tok, done, n_left, req_keys, samp_i),
                None, length=self.sync_every)
            return cache, tok, done, n_left, samp_i, toks

        self._step_jit = self._compile(
            step, ("cache", "vars") + ("rep",) * 5,
            ("cache",) + ("rep",) * 5, _donate_cache())
        return self._step_jit

    def _spec_step_fn(self):
        """Compile (once): ``sync_every`` speculative rounds as a
        ``lax.scan``. One round = ``draft_len`` single-token draft steps
        over the draft pool, then ONE ``s = draft_len + 1`` paged target
        step verifying the block, then a PER-SLOT rollback of both
        pools to their accepted lengths.

        Invariant carried between rounds (same as lock-step
        ``speculative_generate``): each live slot holds a PENDING token
        — emitted to the caller but in NEITHER cache. The round writes
        it as the verify chunk's first position, so the chunk is
        ``[pending, d1 .. d_{draft_len}]`` and the target's greedy
        prediction at chunk position ``i`` continues the true prefix —
        emitted tokens are exactly the target's sequential greedy
        stream, token-identical to the non-speculative engine. Per-slot
        acceptance ``e`` (1..k accepted tokens, 0 for done slots) rides
        the scan output next to the predictions; both pools roll back
        to ``len0 + e`` (chunk prefix kept, new pending token
        ``preds[e-1]`` left unwritten — the invariant restored)."""
        if self._spec_step_jit is not None:
            return self._spec_step_jit
        model, draft = self.model, self.draft_model
        eos = self.eos_token_id
        k = self.draft_len + 1
        arange = jnp.arange(self.num_slots)

        def one_round(variables, dvariables, carry, _):
            cache, dcache, tok, done, n_left = carry
            len0, dlen0 = cache["len"], dcache["len"]

            def draft_step(dcarry, _):
                dc, t_in = dcarry
                lg, dc = draft.apply(dvariables, t_in[:, None], cache=dc)
                nxt = _greedy_token(lg[:, 0], self.axis_name)
                return (dc, nxt), t_in

            # stacked INPUTS of k draft steps = [pending, d1..d_{k-1}]:
            # the k-th draft output is never proposed, but its k cache
            # writes are exactly the chunk — the draft pool stays in
            # lock-step with the target pool through the shared rollback
            (dcache, _), toks_in = lax.scan(draft_step, (dcache, tok),
                                            None, length=k)
            chunk = toks_in.transpose(1, 0)                  # (slots, k)

            logits, cache = model.apply(variables, chunk, cache=cache)
            preds = _greedy_token(logits, self.axis_name)    # (slots, k)
            props = chunk[:, 1:]
            # accepted proposals = longest matching prefix against the
            # target's own predictions; +1 for the bonus target token
            m = jnp.sum(jnp.cumprod(
                (props == preds[:, :-1]).astype(jnp.int32), axis=1),
                axis=1)
            e = jnp.minimum(m + 1, n_left)
            if eos is not None:
                iseos = preds == eos
                has_eos = jnp.any(iseos, axis=1)
                eos_idx = jnp.argmax(iseos, axis=1)
                # never emit past the first EOS prediction
                e = jnp.minimum(e, jnp.where(has_eos, eos_idx + 1, k))
            e = jnp.where(done, 0, e)
            # per-slot rollback of BOTH pools: chunk[:e] stays, the new
            # pending token preds[e-1] stays unwritten; done slots
            # freeze at len0 (their forward wrote only above-len
            # garbage, same as the non-speculative step's frozen slots)
            cache = dict(cache, len=len0 + e)
            dcache = dict(dcache, len=dlen0 + e)
            fill = jnp.int32(eos if eos is not None else 0)
            tok = jnp.where(done, fill,
                            preds[arange, jnp.clip(e - 1, 0, k - 1)])
            n_left = n_left - e
            if eos is not None:
                done = jnp.logical_or(
                    done, jnp.logical_and(has_eos, e == eos_idx + 1))
            done = jnp.logical_or(done, n_left <= 0)
            return (cache, dcache, tok, done, n_left), (preds, e)

        def step(cache, dcache, variables, dvariables, tok, done, n_left):
            ((cache, dcache, tok, done, n_left),
             (toks, counts)) = lax.scan(
                functools.partial(one_round, variables, dvariables),
                (cache, dcache, tok, done, n_left), None,
                length=self.sync_every)
            return cache, dcache, tok, done, n_left, toks, counts

        donate = (0, 1) if jax.default_backend() == "tpu" else ()
        self._spec_step_jit = self._compile(
            step, ("cache", "draft_cache", "vars", "draft_vars")
            + ("rep",) * 3,
            ("cache", "draft_cache") + ("rep",) * 5, donate)
        return self._spec_step_jit

    # --- the host scheduling loop -------------------------------------------

    def _validate_request(self, r: Request) -> None:
        """Reject a request the engine could never serve (position-table
        overflow, block-table overflow, empty budget) — raised at
        ``submit()``/``run()`` time, before any device work."""
        cfg, ps = self.cfg, self.page_size
        max_pages = self.cache["block_tables"].shape[1]
        s0 = int(np.asarray(r.prompt).shape[0])
        if r.max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        if s0 + r.max_new_tokens > cfg.max_position_embeddings:
            raise ValueError(
                f"prompt ({s0}) + max_new_tokens ({r.max_new_tokens}) "
                f"exceeds max_position_embeddings="
                f"{cfg.max_position_embeddings}")
        if kv_pool.pages_for(s0 + r.max_new_tokens, ps) > max_pages:
            raise ValueError(
                f"request needs more than max_pages_per_seq="
                f"{max_pages} pages")
        if self.draft_len:
            # a speculative round may write up to draft_len tokens past
            # the final emitted one before rollback discards them — the
            # position table and block table must absorb the overshoot
            # in BOTH models (mirrors speculative_generate's bound)
            k = self.draft_len + 1
            lim = min(cfg.max_position_embeddings,
                      self.draft_model.config.max_position_embeddings)
            if s0 + r.max_new_tokens + k > lim:
                raise ValueError(
                    f"prompt ({s0}) + max_new_tokens "
                    f"({r.max_new_tokens}) + draft block ({k}) exceeds "
                    f"max_position_embeddings={lim} under speculative "
                    f"decode")
            if kv_pool.pages_for(s0 + r.max_new_tokens + k, ps) > max_pages:
                raise ValueError(
                    f"request + draft-block overshoot needs more than "
                    f"max_pages_per_seq={max_pages} pages under "
                    f"speculative decode")

    def run(self, requests: Sequence[Request], *,
            tracer: Optional[SpanTracer] = None, policy=None):
        """Drain the request queue; returns ``(outputs, stats)``.

        A thin closed-loop wrapper over the serving front-end
        (``serving/frontend.py``): every request is submitted to a fresh
        :class:`~apex_tpu.serving.frontend.ServingFrontend` (so its
        tracer and stats are run-scoped) and the pump is driven
        synchronously until the queue drains — ``run()`` therefore
        exercises exactly the code path a streaming server does,
        including the pipelined decode-chunk pump and, when ``policy``
        enables it and requests carry priorities/deadlines, preemption.
        ``policy`` defaults to
        :class:`~apex_tpu.serving.policy.PriorityDeadlinePolicy`, which
        on plain requests (priority 0, no deadlines) reduces to the
        engine's original FIFO order and never preempts.

        ``outputs[i]``: np.int32 generated tokens for request ``i`` —
        length ``max_new_tokens``, or shorter when the request hit EOS
        (the EOS token is included). ``stats``: engine counters for this
        run, DERIVED from the ``serving.*`` instrument registry
        (``apex_tpu.utils.metrics``) as the delta of each counter across
        the run — ``decode_steps`` / ``admitted`` / ``retired`` /
        ``peak_slots_in_use`` / ``slot_occupancy``, the prefix-cache
        counters (``prefix_hits``, ``prefix_hit_rate``,
        ``prefill_tokens_{total,computed,skipped}``, ``evicted_pages``,
        ``prefix_cached_pages``), the maintenance counters
        (``deferred_admissions``, ``defrag_runs``), the frontend
        counters (``preemptions``, ``resumes``, ``deadline_misses``,
        ``peak_queue_depth``), and this run's latency percentiles
        (``ttft_ms_p50/p95``, ``tpot_ms_p50/p95``,
        ``queue_wait_ms_p50/p95``, ``decode_step_ms_p50/p95``). Every
        numeric stat is also recorded as a ``serving.<name>`` raw series.

        Per-request lifecycle spans (enqueue → admit → prefill →
        first_token → decode → [preempt → preempted → resume →] retire)
        land in a fresh :class:`~apex_tpu.obs.spans.SpanTracer` kept as
        ``self.tracer`` (pass ``tracer=`` to supply your own);
        scheduling events append to the engine-lifetime ``self.events``
        ring (docs/observability.md).
        """
        # the frontend lives below the engine module (it drives the
        # engine's compiled programs); import here to avoid the cycle
        from apex_tpu.serving.frontend import ServingFrontend

        # validate the whole batch up front: a bad request raises before
        # any of its siblings start (the pre-frontend contract)
        for r in requests:
            self._validate_request(r)
        frontend = ServingFrontend(self, policy=policy, tracer=tracer)
        handles = [frontend.submit(r, request_id=i)
                   for i, r in enumerate(requests)]
        frontend.drain()
        outputs = [np.asarray(h.result(timeout=0), np.int32)
                   for h in handles]
        return outputs, frontend.stats()


# the host scheduling loop driving the jitted admit/step programs;
# tpu-lint: host-boundary -- never traced (jit of paged generate is
# unsupported by contract: the engine syncs at every chunk boundary)
def generate_paged(model, variables, prompt_ids, max_new_tokens: int, *,
                   temperature: float = 0.0, top_k: Optional[int] = None,
                   top_p: Optional[float] = None, rng=None,
                   eos_token_id: Optional[int] = None,
                   axis_name: str = MODEL_AXIS,
                   num_slots: Optional[int] = None, page_size: int = 16,
                   num_pages: Optional[int] = None, sync_every: int = 1,
                   prefix_cache: bool = False, return_stats: bool = False,
                   kv_dtype=None):
    """`generate`-shaped front end over the engine.

    ``prompt_ids`` may be a rectangular ``(batch, s0)`` array (the
    ``generate`` contract — returns ``(batch, s0 + max_new_tokens)`` with
    prompts included and EOS padding after a row finishes, matching
    lock-step output exactly under greedy decode) or a list of 1-D
    prompts of MIXED lengths (returns a list of 1-D outputs).
    ``prefix_cache=True`` turns on cross-request shared-prefix KV reuse
    (same outputs, fewer prefill tokens on shared-prefix workloads)."""
    rect = hasattr(prompt_ids, "ndim") and prompt_ids.ndim == 2
    prompts = [np.asarray(p, np.int32).reshape(-1)
               for p in (prompt_ids if not rect else np.asarray(prompt_ids))]
    engine = PagedDecodeEngine(
        model, variables,
        num_slots=num_slots if num_slots is not None else len(prompts),
        page_size=page_size, num_pages=num_pages,
        eos_token_id=eos_token_id, temperature=temperature, top_k=top_k,
        top_p=top_p, rng=rng, sync_every=sync_every, axis_name=axis_name,
        prefix_cache=prefix_cache, kv_dtype=kv_dtype)
    reqs = [Request(prompt=p, max_new_tokens=max_new_tokens)
            for p in prompts]
    outs, stats = engine.run(reqs)

    fill = eos_token_id if eos_token_id is not None else 0
    full = []
    for p, g in zip(prompts, outs):
        g = np.asarray(g, np.int32)
        pad = np.full((max_new_tokens - g.shape[0],), fill, np.int32)
        full.append(np.concatenate([p, g, pad]))
    if rect:
        out = jnp.asarray(np.stack(full))
        return (out, stats) if return_stats else out
    out = [jnp.asarray(f) for f in full]
    return (out, stats) if return_stats else out
