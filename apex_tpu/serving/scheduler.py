"""Continuous-batching decode engine over the paged KV pool.

Iteration-level scheduling (Orca, Yu et al. 2022): a fixed array of
``num_slots`` decode slots advances one token per engine step in a SINGLE
jitted program; at step boundaries the host retires finished slots (EOS or
token budget — their pages return to the free stack immediately) and
admits queued requests into the vacancies. Short requests therefore never
pad to the batch's longest, and a drained slot is re-filled instead of
idling until the batch ends — the two wastes of lock-step ``generate``.

Static shapes throughout: admission PREFILLS through the models' existing
contiguous flash path at a page-size-rounded prompt bucket (one compile
per bucket, reused forever), scatters the resulting K/V into the slot's
pages, and the decode step is one program at one shape. Inside the step
scan the carry holds per-slot (token, EOS-done mask, remaining-token
count) — a finished slot keeps emitting EOS at its frozen state until the
host syncs, exactly like ``decode_loop``'s EOS rows, so ``sync_every > 1``
trades host syncs for (bounded) post-finish padding steps.

Sampling reuses ``models/generation``'s helpers. Greedy decode is
token-identical to per-request lock-step ``generate``; sampled decode
derives each request's key stream from ``fold_in(rng, request_index)`` so
outputs are SCHEDULING-INVARIANT (they depend on the request and the key,
not on which slot or step the request landed in — stronger than lock-step,
whose draws change with batch composition).

``prefix_cache=True`` adds cross-request KV reuse (RadixAttention, Zheng
et al. 2023; ``serving/prefix_cache.py``): admission walks a radix tree
of cached full pages, points the slot's block table at the matched pages
(refcounted, read-only) and prefills only the uncached tail through
``make_shared_admit``; retirement moves the request's full-page prefix
into the tree instead of the free stack, and the stack is replenished by
LRU eviction of refcount-0 cached pages on demand. Greedy outputs stay
token-identical to the cache-off engine: the shared pages replay
bitwise-stored K/V, never re-derived. (The re-prefilled TAIL of a hit
rides dense cached attention where the cold path rides the flash kernel
— exact in fp32; under bf16 the two summation orders can differ in low
bits, so a near-tied argmax could flip, the same caveat as
``speculative_generate``'s chunked-verify exactness note.)
"""

from __future__ import annotations

import dataclasses
import functools
import itertools
import time
from collections import deque
from typing import Any, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from apex_tpu.mesh import MODEL_AXIS
from apex_tpu.models.generation import (_greedy_token, _sample_token,
                                        init_cache, validate_sampling)
from apex_tpu.obs.events import EventLog
from apex_tpu.obs.spans import SpanTracer
from apex_tpu.ops._dispatch import round_up
from apex_tpu.serving import kv_pool
from apex_tpu.serving.prefix_cache import PrefixCache
from apex_tpu.utils import metrics

#: run() counters in the instrument registry (``serving.<name>``); the
#: per-run stats dict is the DELTA of these across the run — the registry
#: is the state of record, the dict a derived view
_RUN_COUNTERS = ("admitted", "retired", "decode_steps", "busy_slot_steps",
                 "prefix_hits", "prefill_tokens_total",
                 "prefill_tokens_computed", "evicted_pages",
                 "deferred_admissions", "defrag_runs")

#: per-request latency histograms (``serving.<name>``, log-bucketed ms)
_RUN_HISTOGRAMS = ("ttft_ms", "tpot_ms", "queue_wait_ms", "decode_step_ms")

#: per-process engine ids, the ``engine`` label on run counters
_ENGINE_IDS = itertools.count()


@dataclasses.dataclass
class Request:
    """One decode request: a 1-D int32 prompt and its token budget."""

    prompt: Any                      # (s0,) int array
    max_new_tokens: int


def _donate_cache():
    # buffer donation keeps the page pool in place across step/admit calls
    # on TPU; the CPU backend has no donation and would warn every call
    return (0,) if jax.default_backend() == "tpu" else ()


def prompt_bucket(s0: int, page_size: int, max_positions: int) -> int:
    """The admission compile-key bucket for a raw prompt length: pad up
    to a whole page (capped at the position table) so one program serves
    every length in the page — the compile-count contract the IR tier's
    ``gpt2s_engine_admit_bucketed`` case traces at two same-bucket
    lengths (``ir-compile-key-cardinality``). Admission and the lint
    harness MUST share this function: the contract is only binding on
    the engine if the engine's own bucketing is what gets traced."""
    return min(round_up(max(s0, 1), page_size), max_positions)


def _bucket_match_pages(m: int) -> int:
    """Round a radix match depth DOWN to a power of two pages. Retirement
    inserts prompts AND generated tokens, so raw match depths take many
    distinct values — and every distinct ``t_start`` is a fresh
    shared-admit XLA compile stalling the admission loop. The power-of-two
    floor bounds the compile-key set at ``log2(max_pages)`` per tail
    bucket, at the cost of re-prefilling at most half the matched pages
    (none at all for power-of-two-page shared headers, the common case)."""
    return 1 << (m.bit_length() - 1) if m > 0 else 0


def make_shared_admit(model, *, t_start: int, tail_bucket: int,
                      first_token=None, axis_name: str = MODEL_AXIS):
    """Build the shared-prefix admission program (one compile per
    ``(t_start, tail_bucket)`` pair, cached by the engine; also the
    ``tpu_aot.py`` sweep's prefix-cached decode case).

    The matched prefix (``t_start`` tokens = ``t_start/page_size`` whole
    cached pages) is GATHERED from the pool into a contiguous buffer, and
    the model forward runs over ONLY the ``tail_bucket``-padded uncached
    tail with the buffer as its KV cache at static length ``t_start`` —
    the tail attends over the shared prefix through the models' existing
    cached path, but the prefix contributes zero forward FLOPs. The tail's
    K/V then scatters into the slot's private pages
    (``prefill_into_pages(start=t_start)`` — shared pages are never
    written: copy-on-write at page granularity, the partially-filled
    boundary page is always private) and the first token samples from the
    prompt-final logits.

    Returns ``admit(cache, variables, tail_ids, s0, slot, shared_row,
    n_private, req_key) -> (cache, tok0)`` where ``shared_row`` is a
    ``(max_pages,)`` int32 row whose first ``t_start/page_size`` entries
    are the matched physical pages."""
    cfg = model.config
    if t_start < 1 or tail_bucket < 1:
        raise ValueError("shared admission needs t_start >= 1 matched "
                         "tokens and tail_bucket >= 1 tail tokens")
    if first_token is None:
        def first_token(last, _key):
            return _greedy_token(last, axis_name)
    bucket = t_start + tail_bucket

    def admit(cache, variables, tail_ids, s0, slot, shared_row, n_private,
              req_key):
        ps = kv_pool.page_size_of(cache)
        if t_start % ps:
            raise ValueError(f"t_start={t_start} must be a page multiple "
                             f"({ps})")
        m = t_start // ps
        contig = init_cache(cfg, 1, bucket)
        layers = []
        for pool_lc, lc in zip(cache["layers"], contig["layers"]):
            def gathered(pages, dst):
                # (m, kv, ps, d) page tiles -> the buffer's leading
                # t_start positions
                kv, d = pages.shape[1], pages.shape[3]
                block = pages.transpose(1, 0, 2, 3).reshape(
                    1, kv, t_start, d)
                return dst.at[:, :, :t_start, :].set(
                    block.astype(dst.dtype))
            layers.append(
                {"k": gathered(pool_lc["k_pages"][shared_row[:m]], lc["k"]),
                 "v": gathered(pool_lc["v_pages"][shared_row[:m]], lc["v"])})
        # static len t_start: the tail chunk is a chunked continuation —
        # bounds check at trace time, dense cached attention over the
        # buffer (the flash path needs len 0, which the prefix occupies)
        contig = {"layers": layers, "len": t_start}
        logits, contig = model.apply(variables, tail_ids, cache=contig)
        last = lax.dynamic_slice_in_dim(logits, s0 - t_start - 1, 1,
                                        axis=1)[:, 0]
        cache = kv_pool.alloc_slot_shared(cache, slot, shared_row, m,
                                          n_private)
        cache = kv_pool.prefill_into_pages(cache, slot, contig["layers"],
                                           s0, start=t_start)
        tok0 = first_token(last, req_key)[0]
        return cache, tok0

    return admit


class PagedDecodeEngine:
    """Continuous-batching greedy/sampled decode over ``num_slots`` slots.

    ``run(requests)`` processes the whole queue and returns
    ``(outputs, stats)`` where ``outputs[i]`` is request ``i``'s generated
    tokens (up to and including its first EOS) and ``stats`` counts engine
    decode steps — the serving cost driver lock-step padding inflates.
    """

    def __init__(self, model, variables, *, num_slots: int,
                 page_size: int = 16, num_pages: Optional[int] = None,
                 max_pages_per_seq: Optional[int] = None,
                 eos_token_id: Optional[int] = None,
                 temperature: float = 0.0, top_k: Optional[int] = None,
                 top_p: Optional[float] = None, rng=None,
                 sync_every: int = 1, axis_name: str = MODEL_AXIS,
                 prefix_cache: bool = False):
        cfg = model.config
        if num_slots < 1:
            raise ValueError("num_slots must be >= 1")
        if sync_every < 1:
            raise ValueError("sync_every must be >= 1")
        self.model = model
        self.variables = variables
        self.cfg = cfg
        self.num_slots = num_slots
        self.page_size = page_size
        self.eos_token_id = eos_token_id
        self.temperature = temperature
        self.top_k = top_k
        self.top_p = top_p
        self.rng = validate_sampling(temperature, top_k, top_p, rng)
        self.sync_every = sync_every
        self.axis_name = axis_name
        if max_pages_per_seq is None:
            max_pages_per_seq = kv_pool.cdiv(cfg.max_position_embeddings,
                                             page_size)
        if num_pages is None:
            # worst case: every slot holds a max-length sequence (+ null)
            num_pages = 1 + num_slots * max_pages_per_seq
        self.cache = kv_pool.init_paged_cache(
            cfg, num_slots, num_pages=num_pages, page_size=page_size,
            max_pages_per_seq=max_pages_per_seq)
        # observability (docs/observability.md): a bounded postmortem
        # event ring for the engine's lifetime, and the last run's span
        # tracer (fresh per run; run(tracer=...) injects one). Every
        # serving/pool/prefix instrument carries this ``engine`` label
        # so concurrent engines in one process never mix each other's
        # increments, distributions, or pool-health levels
        self.events = EventLog(capacity=4096)
        self.tracer: Optional[SpanTracer] = None
        self.obs_labels = {"engine": str(next(_ENGINE_IDS))}
        # cross-request KV reuse: the host radix tree naming cached pages
        self.prefix = (PrefixCache(page_size,
                                   metrics_labels=self.obs_labels)
                       if prefix_cache else None)
        self._admit_jit = {}             # prompt bucket -> compiled admit
        self._shared_admit_jit = {}      # (t_start, tail_bucket) -> admit
        self._step_jit = None
        self._free_jit = jax.jit(kv_pool.free_slot,
                                 donate_argnums=_donate_cache())
        self._release_jit = jax.jit(kv_pool.release_slot,
                                    donate_argnums=_donate_cache())
        self._evict_jit = jax.jit(kv_pool.evict_pages,
                                  donate_argnums=_donate_cache())
        self._defrag_jit = jax.jit(kv_pool.defrag_map,
                                   donate_argnums=_donate_cache())

    # --- request-key sampling (scheduling-invariant streams) ----------------

    def _first_token(self, last_logits, req_key):
        if not self.temperature:
            return _greedy_token(last_logits, self.axis_name)
        return _sample_token(last_logits, jax.random.fold_in(req_key, 0),
                             temperature=self.temperature, top_k=self.top_k,
                             top_p=self.top_p, axis_name=self.axis_name)

    # --- compiled programs --------------------------------------------------

    def _admit_fn(self, bucket: int):
        """Compile (once per prompt bucket): contiguous flash prefill at
        ``bucket`` tokens, page alloc + scatter, first-token sample."""
        if bucket in self._admit_jit:
            return self._admit_jit[bucket]
        model = self.model                       # static via closure

        def admit(cache, variables, ids, s0, slot, n_pages, req_key):
            contig = init_cache(self.cfg, 1, bucket)
            logits, contig = model.apply(variables, ids, cache=contig)
            last = lax.dynamic_slice_in_dim(logits, s0 - 1, 1, axis=1)[:, 0]
            cache = kv_pool.alloc_slot(cache, slot, n_pages)
            cache = kv_pool.prefill_into_pages(cache, slot,
                                               contig["layers"], s0)
            tok0 = self._first_token(last, req_key)[0]
            return cache, tok0

        fn = jax.jit(admit, donate_argnums=_donate_cache())
        self._admit_jit[bucket] = fn
        return fn

    def _admit_shared_fn(self, t_start: int, tail_bucket: int):
        """Compile (once per ``(t_start, tail_bucket)``): the shared-prefix
        admission — gather matched pages, tail-only prefill, page-pool
        scatter, first-token sample (``make_shared_admit``)."""
        key = (t_start, tail_bucket)
        if key not in self._shared_admit_jit:
            fn = make_shared_admit(self.model, t_start=t_start,
                                   tail_bucket=tail_bucket,
                                   first_token=self._first_token,
                                   axis_name=self.axis_name)
            self._shared_admit_jit[key] = jax.jit(
                fn, donate_argnums=_donate_cache())
        return self._shared_admit_jit[key]

    # --- pool maintenance ---------------------------------------------------

    def _leak_suspected(self, free: int, active) -> bool:
        """True when host liveness accounting says more pages should be
        free than the stack shows — a free miscount somewhere; ``defrag``
        rebuilds the stack from actual liveness and recovers them."""
        owned = sum(rec["n_private"] for rec in active.values())
        cached = len(self.prefix) if self.prefix is not None else 0
        usable = kv_pool.num_pages_of(self.cache) - 1    # null page
        return usable - owned - cached > free

    def _defrag_now(self):
        """Run ``defrag_map`` with the prefix cache's resident pages as
        extra liveness (they appear in no block table but must survive),
        then remap the radix tree through the returned page permutation."""
        num_pages = kv_pool.num_pages_of(self.cache)
        extra = np.zeros((num_pages,), bool)
        if self.prefix is not None:
            extra[self.prefix.pages()] = True
        self.cache, new_idx = self._defrag_jit(self.cache,
                                               jnp.asarray(extra))
        if self.prefix is not None:
            self.prefix.remap(np.asarray(new_idx))

    def _step_fn(self):
        """Compile (once): ``sync_every`` decode steps as a ``lax.scan``
        whose carry holds the paged cache and per-slot (token, done mask,
        remaining-token count)."""
        if self._step_jit is not None:
            return self._step_jit
        model = self.model
        eos = self.eos_token_id

        def one_step(variables, carry, _):
            cache, tok, done, n_left, req_keys, samp_i = carry
            len_before = cache["len"]
            logits, cache = model.apply(variables, tok[:, None], cache=cache)
            # freeze done/idle slots' lengths: their forward ran (static
            # shapes) against the null-page sink, but their position must
            # not creep — unbounded growth would walk the position table
            # and scale null-page attention work with idle time
            cache = dict(cache, len=jnp.where(done, len_before,
                                              cache["len"]))
            last = logits[:, 0]
            if not self.temperature:
                nxt = _greedy_token(last, self.axis_name)
            else:
                # key = fold_in(request key, the request's OWN token
                # index) -> draws are scheduling-invariant (independent of
                # slot, step, and batch composition)
                keys = jax.vmap(jax.random.fold_in)(req_keys, samp_i)
                nxt = jax.vmap(
                    lambda lg, k: _sample_token(
                        lg[None], k, temperature=self.temperature,
                        top_k=self.top_k, top_p=self.top_p,
                        axis_name=self.axis_name)[0])(last, keys)
            fill = jnp.int32(eos if eos is not None else 0)
            nxt = jnp.where(done, fill, nxt)
            n_left = jnp.where(done, n_left, n_left - 1)
            samp_i = samp_i + 1
            if eos is not None:
                done = jnp.logical_or(done, nxt == eos)
            done = jnp.logical_or(done, n_left <= 0)
            return (cache, nxt, done, n_left, req_keys, samp_i), nxt

        def step(cache, variables, tok, done, n_left, req_keys, samp_i):
            # greedy mode never reads req_keys; the carry layout stays
            # identical across greedy/sampled so both share one step
            # tpu-lint: disable=ir-dead-scan-carry -- (slots, 2) u32/step
            (cache, tok, done, n_left, _, samp_i), toks = lax.scan(
                functools.partial(one_step, variables),
                (cache, tok, done, n_left, req_keys, samp_i),
                None, length=self.sync_every)
            return cache, tok, done, n_left, samp_i, toks

        self._step_jit = jax.jit(step, donate_argnums=_donate_cache())
        return self._step_jit

    # --- the host scheduling loop -------------------------------------------

    def run(self, requests: Sequence[Request], *,
            tracer: Optional[SpanTracer] = None):
        """Drain the request queue; returns ``(outputs, stats)``.

        ``outputs[i]``: np.int32 generated tokens for request ``i`` —
        length ``max_new_tokens``, or shorter when the request hit EOS
        (the EOS token is included). ``stats``: engine counters for this
        run, DERIVED from the ``serving.*`` instrument registry
        (``apex_tpu.utils.metrics``) as the delta of each counter across
        the run — ``decode_steps`` / ``admitted`` / ``retired`` /
        ``peak_slots_in_use`` / ``slot_occupancy``, the prefix-cache
        counters (``prefix_hits``, ``prefix_hit_rate``,
        ``prefill_tokens_{total,computed,skipped}``, ``evicted_pages``,
        ``prefix_cached_pages``), the maintenance counters
        (``deferred_admissions``, ``defrag_runs``), and this run's
        latency percentiles (``ttft_ms_p50/p95``, ``tpot_ms_p50/p95``,
        ``queue_wait_ms_p50/p95``, ``decode_step_ms_p50/p95``). Every
        numeric stat is also recorded as a ``serving.<name>`` raw series.

        Per-request lifecycle spans (enqueue → admit → prefill →
        first_token → decode → retire) land in a fresh
        :class:`~apex_tpu.obs.spans.SpanTracer` kept as ``self.tracer``
        (pass ``tracer=`` to supply your own); scheduling events append
        to the engine-lifetime ``self.events`` ring
        (docs/observability.md).
        """
        cfg, ps = self.cfg, self.page_size
        max_pages = self.cache["block_tables"].shape[1]
        for r in requests:
            s0 = int(np.asarray(r.prompt).shape[0])
            if r.max_new_tokens < 1:
                raise ValueError("max_new_tokens must be >= 1")
            if s0 + r.max_new_tokens > cfg.max_position_embeddings:
                raise ValueError(
                    f"prompt ({s0}) + max_new_tokens ({r.max_new_tokens}) "
                    f"exceeds max_position_embeddings="
                    f"{cfg.max_position_embeddings}")
            if kv_pool.pages_for(s0 + r.max_new_tokens, ps) > max_pages:
                raise ValueError(
                    f"request needs more than max_pages_per_seq="
                    f"{max_pages} pages")

        tr = tracer if tracer is not None else SpanTracer()
        self.tracer = tr
        C = {n: metrics.counter(f"serving.{n}", labels=self.obs_labels)
             for n in _RUN_COUNTERS}
        c0 = {n: C[n].value for n in C}   # run-start snapshot -> deltas
        H = {n: metrics.histogram(f"serving.{n}", labels=self.obs_labels)
             for n in _RUN_HISTOGRAMS}
        occ_gauge = metrics.gauge("serving.slots_in_use",
                                  labels=self.obs_labels)
        per_run = {n: [] for n in _RUN_HISTOGRAMS}

        queue = deque(enumerate(requests))
        for idx, req in queue:
            # np.shape reads the length without a device->host transfer
            tr.event(idx, "enqueue",
                     prompt_tokens=int(np.shape(req.prompt)[0]),
                     max_new_tokens=req.max_new_tokens)
        outputs: List[Optional[np.ndarray]] = [None] * len(requests)
        active = {}                       # slot -> mutable request record
        tok = jnp.zeros((self.num_slots,), jnp.int32)
        done = jnp.ones((self.num_slots,), bool)
        n_left = jnp.zeros((self.num_slots,), jnp.int32)
        samp_i = jnp.zeros((self.num_slots,), jnp.int32)
        req_keys = jnp.broadcast_to(self.rng, (self.num_slots,)
                                    + self.rng.shape)
        peak = 0

        def observe_lifecycle(idx):
            life = tr.lifecycle(idx)
            for name in ("ttft_ms", "tpot_ms", "queue_wait_ms"):
                if name in life:
                    H[name].observe(life[name])
                    per_run[name].append(life[name])

        def retire(slot):
            rec = active.pop(slot)
            outputs[rec["idx"]] = np.asarray(rec["tokens"], np.int32)
            C["retired"].inc()
            n_new = len(rec["tokens"])
            tr.end(rec["idx"], "decode", new_tokens=n_new)
            tr.event(rec["idx"], "retire", slot=slot, new_tokens=n_new)
            self.events.emit("retire", request=rec["idx"], slot=slot,
                             new_tokens=n_new)
            observe_lifecycle(rec["idx"])
            if self.prefix is None:
                self.cache = self._free_jit(self.cache, jnp.int32(slot))
                return
            # written K/V = prompt + every token fed while alive (all but
            # the final sampled token, which retires before its step);
            # only full pages of that enter the tree — the partial
            # boundary page (and the frozen-done garbage position right
            # at ``written``) never becomes shareable
            written = rec["s0"] + len(rec["tokens"]) - 1
            seq = np.concatenate(
                [rec["prompt"], np.asarray(rec["tokens"][:-1], np.int32)])
            row = np.asarray(self.cache["block_tables"][slot])
            keep = self.prefix.release_and_insert(seq, written,
                                                  rec["nodes"], row)
            self.cache = self._release_jit(self.cache, jnp.int32(slot),
                                           jnp.asarray(keep))

        while queue or active:
            # --- admission: fill vacant slots while pages last ----------
            free_slots = [s for s in range(self.num_slots)
                          if s not in active]
            admitted_any = False
            for slot in free_slots:
                if not queue:
                    break
                idx, req = queue[0]
                prompt = np.asarray(req.prompt, np.int32).reshape(-1)
                s0 = prompt.shape[0]
                need_total = kv_pool.pages_for(s0 + req.max_new_tokens, ps)
                # prefix match BEFORE the page check: matched pages are
                # shared, not allocated, so they shrink the demand.
                # Acquire immediately — the eviction below must see the
                # matched nodes as pinned, not as LRU victims
                nodes = (self.prefix.match(prompt)
                         if self.prefix is not None else [])
                # bucket the match depth (compile-count bound); the
                # dropped tail of the match re-prefills and dedups back
                # into the tree at retirement
                nodes = nodes[:_bucket_match_pages(len(nodes))]
                if nodes:
                    self.prefix.acquire(nodes)
                m = len(nodes)
                need = need_total - m
                free = int(kv_pool.free_page_count(self.cache))
                if free < need and self.prefix is not None:
                    # replenish the stack: LRU refcount-0 cached pages
                    pages = self.prefix.evict(need - free)
                    if pages:
                        row = np.zeros((max_pages,), np.int32)
                        row[:len(pages)] = pages
                        self.cache = self._evict_jit(
                            self.cache, jnp.asarray(row),
                            jnp.int32(len(pages)))
                        C["evicted_pages"].inc(len(pages))
                        self.events.emit("evict", request=idx,
                                         pages=len(pages))
                        free += len(pages)
                if free < need and self._leak_suspected(free, active):
                    # liveness says more pages exist than the stack shows:
                    # compact + rebuild the stack, remap the radix tree
                    self._defrag_now()
                    C["defrag_runs"].inc()
                    self.events.emit("defrag", request=idx)
                    free = int(kv_pool.free_page_count(self.cache))
                if free < need:
                    if nodes:
                        self.prefix.release(nodes)
                    C["deferred_admissions"].inc()
                    self.events.emit("defer", request=idx, need_pages=need,
                                     free_pages=free)
                    break                 # head-of-line: wait for pages
                queue.popleft()
                tr.event(idx, "admit", slot=slot, free_pages=free,
                         cached_pages=m)
                req_key = jax.random.fold_in(self.rng, idx)
                # prefill span: covers the admission program AND the
                # first-token sync — its end IS the first token's arrival
                with tr.span(idx, "prefill", cached_tokens=m * ps,
                             computed_tokens=s0 - m * ps):
                    if m == 0:
                        bucket = prompt_bucket(
                            s0, ps, cfg.max_position_embeddings)
                        ids = np.zeros((1, bucket), np.int32)
                        ids[0, :s0] = prompt
                        self.cache, tok0 = self._admit_fn(bucket)(
                            self.cache, self.variables, jnp.asarray(ids),
                            jnp.int32(s0), jnp.int32(slot), jnp.int32(need),
                            req_key)
                    else:
                        C["prefix_hits"].inc()
                        t_start = m * ps
                        tail_bucket = min(round_up(s0 - t_start, ps),
                                          cfg.max_position_embeddings
                                          - t_start)
                        ids = np.zeros((1, tail_bucket), np.int32)
                        ids[0, :s0 - t_start] = prompt[t_start:]
                        row = np.zeros((max_pages,), np.int32)
                        row[:m] = [n.page for n in nodes]
                        self.cache, tok0 = self._admit_shared_fn(
                            t_start, tail_bucket)(
                            self.cache, self.variables, jnp.asarray(ids),
                            jnp.int32(s0), jnp.int32(slot),
                            jnp.asarray(row), jnp.int32(need), req_key)
                    tok0 = int(tok0)
                tr.event(idx, "first_token", slot=slot)
                tr.begin(idx, "decode", slot=slot)
                C["admitted"].inc()
                C["prefill_tokens_total"].inc(s0)
                C["prefill_tokens_computed"].inc(s0 - m * ps)
                self.events.emit("admit", request=idx, slot=slot,
                                 prompt_tokens=s0, cached_tokens=m * ps)
                rec = {"idx": idx, "tokens": [tok0],
                       "max_new": req.max_new_tokens, "prompt": prompt,
                       "s0": s0, "nodes": nodes, "n_private": need}
                active[slot] = rec
                admitted_any = True
                if (self.eos_token_id is not None
                        and tok0 == self.eos_token_id) \
                        or req.max_new_tokens == 1:
                    retire(slot)
                    continue
                tok = tok.at[slot].set(tok0)
                done = done.at[slot].set(False)
                n_left = n_left.at[slot].set(req.max_new_tokens - 1)
                samp_i = samp_i.at[slot].set(1)   # token 0 drawn at admit
                req_keys = req_keys.at[slot].set(req_key)
            if not active:
                if queue and not admitted_any:
                    raise RuntimeError(
                        "scheduler deadlock: queued request cannot be "
                        "admitted even with every slot vacant and every "
                        "evictable cached page evicted (pool too small "
                        "for its page demand?)")
                continue
            peak = max(peak, len(active))
            occ_gauge.set(len(active))

            # --- one jitted multi-step decode chunk ---------------------
            C["busy_slot_steps"].inc(len(active) * self.sync_every)
            t_chunk = time.perf_counter()
            self.cache, tok, done, n_left, samp_i, toks = self._step_fn()(
                self.cache, self.variables, tok, done, n_left, req_keys,
                samp_i)
            toks_np = np.asarray(toks)               # (sync_every, slots)
            # per-step wall time, synced at the harvest (with
            # sync_every > 1 this is the chunk's per-step mean)
            step_ms = ((time.perf_counter() - t_chunk) * 1e3
                       / self.sync_every)
            H["decode_step_ms"].observe(step_ms)
            per_run["decode_step_ms"].append(step_ms)
            C["decode_steps"].inc(self.sync_every)

            # --- harvest + retirement at the sync boundary --------------
            n_retired_chunk = 0
            for slot in list(active):
                rec = active[slot]
                finished = False
                for t in toks_np[:, slot]:
                    t = int(t)
                    rec["tokens"].append(t)
                    if ((self.eos_token_id is not None
                         and t == self.eos_token_id)
                            or len(rec["tokens"]) >= rec["max_new"]):
                        finished = True
                        break
                if finished:
                    retire(slot)
                    done = done.at[slot].set(True)
                    n_retired_chunk += 1

            # pool health gauges (free pages, active sharing refcounts —
            # docs/observability.md catalog): only at boundaries where
            # the pool actually changed (admission/retirement), so
            # steady decode-only chunks pay no extra device->host reads
            if admitted_any or n_retired_chunk:
                kv_pool.observe_pool(self.cache, labels=self.obs_labels)

        # final state after the drain
        kv_pool.observe_pool(self.cache, labels=self.obs_labels)
        occ_gauge.set(0)
        d = {n: C[n].value - c0[n] for n in C}   # this run's contribution
        stats = {
            "decode_steps": int(d["decode_steps"]),
            "admitted": int(d["admitted"]),
            "retired": int(d["retired"]), "peak_slots_in_use": peak,
            "slot_occupancy": (d["busy_slot_steps"]
                               / max(d["decode_steps"] * self.num_slots,
                                     1)),
            "deferred_admissions": int(d["deferred_admissions"]),
            "defrag_runs": int(d["defrag_runs"]),
            "prefix_cache_enabled": self.prefix is not None,
            "prefix_hits": int(d["prefix_hits"]),
            "prefix_hit_rate": d["prefix_hits"] / max(d["admitted"], 1),
            "prefix_cached_pages": (len(self.prefix)
                                    if self.prefix is not None else 0),
            "evicted_pages": int(d["evicted_pages"]),
            "prefill_tokens_total": int(d["prefill_tokens_total"]),
            "prefill_tokens_computed": int(d["prefill_tokens_computed"]),
            "prefill_tokens_skipped": int(d["prefill_tokens_total"]
                                          - d["prefill_tokens_computed"]),
        }
        # this run's latency percentiles (the global histograms hold the
        # engine-lifetime distributions; these are run-local and exact)
        for name, vals in per_run.items():
            if vals:
                stats[f"{name}_p50"] = float(np.percentile(vals, 50))
                stats[f"{name}_p95"] = float(np.percentile(vals, 95))
        for name, val in stats.items():
            if isinstance(val, bool):
                continue
            metrics.record(f"serving.{name}", val)
        return outputs, stats


# the host scheduling loop driving the jitted admit/step programs;
# tpu-lint: host-boundary -- never traced (jit of paged generate is
# unsupported by contract: the engine syncs at every chunk boundary)
def generate_paged(model, variables, prompt_ids, max_new_tokens: int, *,
                   temperature: float = 0.0, top_k: Optional[int] = None,
                   top_p: Optional[float] = None, rng=None,
                   eos_token_id: Optional[int] = None,
                   axis_name: str = MODEL_AXIS,
                   num_slots: Optional[int] = None, page_size: int = 16,
                   num_pages: Optional[int] = None, sync_every: int = 1,
                   prefix_cache: bool = False, return_stats: bool = False):
    """`generate`-shaped front end over the engine.

    ``prompt_ids`` may be a rectangular ``(batch, s0)`` array (the
    ``generate`` contract — returns ``(batch, s0 + max_new_tokens)`` with
    prompts included and EOS padding after a row finishes, matching
    lock-step output exactly under greedy decode) or a list of 1-D
    prompts of MIXED lengths (returns a list of 1-D outputs).
    ``prefix_cache=True`` turns on cross-request shared-prefix KV reuse
    (same outputs, fewer prefill tokens on shared-prefix workloads)."""
    rect = hasattr(prompt_ids, "ndim") and prompt_ids.ndim == 2
    prompts = [np.asarray(p, np.int32).reshape(-1)
               for p in (prompt_ids if not rect else np.asarray(prompt_ids))]
    engine = PagedDecodeEngine(
        model, variables,
        num_slots=num_slots if num_slots is not None else len(prompts),
        page_size=page_size, num_pages=num_pages,
        eos_token_id=eos_token_id, temperature=temperature, top_k=top_k,
        top_p=top_p, rng=rng, sync_every=sync_every, axis_name=axis_name,
        prefix_cache=prefix_cache)
    reqs = [Request(prompt=p, max_new_tokens=max_new_tokens)
            for p in prompts]
    outs, stats = engine.run(reqs)

    fill = eos_token_id if eos_token_id is not None else 0
    full = []
    for p, g in zip(prompts, outs):
        g = np.asarray(g, np.int32)
        pad = np.full((max_new_tokens - g.shape[0],), fill, np.int32)
        full.append(np.concatenate([p, g, pad]))
    if rect:
        out = jnp.asarray(np.stack(full))
        return (out, stats) if return_stats else out
    out = [jnp.asarray(f) for f in full]
    return (out, stats) if return_stats else out
