"""The NCCL-equivalent: XLA collectives on named mesh axes.

The reference rides torch.distributed/NCCL for every collective
(apex/parallel/distributed.py all_reduce buckets,
apex/transformer/tensor_parallel/mappings.py TP collectives,
apex/transformer/pipeline_parallel/p2p_communication.py isend/irecv,
apex/contrib/csrc/nccl_p2p/ raw rings). On TPU all of those map onto XLA
collectives over ICI/DCN, addressed by mesh axis *name* inside
``jax.shard_map``/``pjit`` rather than by process group.

These wrappers are intentionally thin — the value is a single place that fixes
naming, axis conventions, and tiled-vs-concat semantics, mirroring the role of
the reference's ``flat_dist_call`` (apex/parallel/distributed.py:~30).

All functions must be called inside ``shard_map`` (or a ``pjit`` body with
manual axes) where ``axis_name`` is bound.
"""

from __future__ import annotations

from typing import Optional, Sequence, Union

import jax
import jax.numpy as jnp
from jax import lax

AxisName = Union[str, Sequence[str]]


def all_reduce(x, axis_name: AxisName = "data", op: str = "sum"):
    """NCCL allreduce equivalent (reference: torch.distributed.all_reduce)."""
    if op == "sum":
        return lax.psum(x, axis_name)
    if op == "mean":
        return lax.pmean(x, axis_name)
    if op == "max":
        return lax.pmax(x, axis_name)
    if op == "min":
        return lax.pmin(x, axis_name)
    raise ValueError(f"unsupported reduce op: {op}")


def all_gather(x, axis_name: AxisName = "model", axis: int = 0, tiled: bool = True):
    """NCCL allgather equivalent; ``tiled=True`` concatenates along ``axis``
    (the reference's gather semantics in
    apex/transformer/tensor_parallel/mappings.py:_GatherFromModelParallelRegion)."""
    return lax.all_gather(x, axis_name, axis=axis, tiled=tiled)


def reduce_scatter(x, axis_name: AxisName = "model", axis: int = 0, tiled: bool = True):
    """NCCL reduce-scatter equivalent (reference:
    mappings.py:_ReduceScatterToSequenceParallelRegion)."""
    return lax.psum_scatter(x, axis_name, scatter_dimension=axis, tiled=tiled)


def all_to_all(x, axis_name: AxisName, split_axis: int, concat_axis: int, tiled: bool = True):
    """NCCL alltoall equivalent (no direct reference use; needed for
    Ulysses-style sequence parallelism — beyond-reference capability)."""
    return lax.all_to_all(x, axis_name, split_axis=split_axis, concat_axis=concat_axis, tiled=tiled)


def broadcast(x, axis_name: AxisName, src_index: int = 0):
    """NCCL broadcast equivalent (reference: flat_dist_call broadcast of
    params rank0 → all in apex/parallel/distributed.py:__init__).

    Implemented as select-then-psum so it works under SPMD.
    """
    idx = lax.axis_index(axis_name)
    masked = jnp.where(idx == src_index, x, jnp.zeros_like(x))
    return lax.psum(masked, axis_name)


def permute(x, axis_name: AxisName, perm: Sequence[tuple]):
    """collective-permute (reference: NCCL send/recv rings in
    apex/contrib/csrc/nccl_p2p/nccl_p2p_cuda.cu)."""
    return lax.ppermute(x, axis_name, perm=perm)


def shift_right(x, axis_name: AxisName, wrap: bool = False):
    """Send to rank+1 / receive from rank-1 along ``axis_name`` — the pipeline
    ``send_forward``/``recv_forward`` pair
    (reference: pipeline_parallel/p2p_communication.py:send_forward).

    With ``wrap=False`` the first rank receives zeros (matching "no previous
    stage" semantics).
    """
    n = lax.axis_size(axis_name)
    if wrap:
        perm = [(i, (i + 1) % n) for i in range(n)]
    else:
        perm = [(i, i + 1) for i in range(n - 1)]
    return lax.ppermute(x, axis_name, perm=perm)


def shift_left(x, axis_name: AxisName, wrap: bool = False):
    """Send to rank-1 / receive from rank+1 — the ``send_backward`` pair
    (reference: p2p_communication.py:send_backward)."""
    n = lax.axis_size(axis_name)
    if wrap:
        perm = [(i, (i - 1) % n) for i in range(n)]
    else:
        perm = [(i, i - 1) for i in range(1, n)]
    return lax.ppermute(x, axis_name, perm=perm)


def axis_index(axis_name: AxisName):
    return lax.axis_index(axis_name)


def axis_size(axis_name: AxisName) -> int:
    return lax.axis_size(axis_name)
