"""BERT for pretraining — the flagship benchmark model (BASELINE.md config #2).

Reference: apex/transformer/testing/standalone_bert.py (test-only vendored
Megatron BERT) and the MLPerf-BERT lineage of apex's kernels (fmha seqlen<=512,
fast_layer_norm hidden sizes 768..1024, DistributedFusedLAMB). This module is
the TPU-native restatement: batch-first [B, S] activations, flash attention
(apex_tpu.ops.flash_attention subsumes fmhalib + fast_multihead_attn), Pallas
FusedLayerNorm, XLA-fused GELU MLP (fused_dense_cuda analog), and the fused
softmax-xentropy loss (xentropy_cuda analog) for MLM + NSP heads.

Parallelism-ready: ``param_partition_specs`` returns Megatron-style
PartitionSpecs over the ``model`` mesh axis (column-split QKV/FFN-in,
row-split out-proj/FFN-out — the sharding ColumnParallelLinear /
RowParallelLinear produce), so the same model runs pure-DP on one chip and
TP x DP on a mesh with XLA inserting the collectives.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from apex_tpu.amp.policy import resolve_compute_dtype
from apex_tpu.mesh import CONTEXT_AXIS, DATA_AXIS, MODEL_AXIS
from apex_tpu.normalization import FusedLayerNorm
from apex_tpu.ops import flash_attention, softmax_cross_entropy


@dataclasses.dataclass(frozen=True)
class BertConfig:
    vocab_size: int = 30528          # 30522 rounded up to a lane multiple
    hidden_size: int = 1024
    num_layers: int = 24
    num_heads: int = 16
    intermediate_size: int = 4096
    max_position_embeddings: int = 512
    type_vocab_size: int = 2
    hidden_dropout: float = 0.1
    attention_dropout: float = 0.1
    layernorm_eps: float = 1e-12
    # tanh-approx GELU (default: TPU-friendly, matches Megatron);
    # False = exact erf GELU (HF BERT checkpoints' hidden_act='gelu')
    gelu_approximate: bool = True
    dtype: Any = jnp.bfloat16        # compute dtype (amp O1/O2 analog)
    param_dtype: Any = jnp.float32
    # per-layer activation rematerialization (same trade as GPTConfig:
    # ~30% more FLOPs in backward for O(1)-layer activation memory —
    # unlocks larger per-chip batches at BERT-Large on 16 GB HBM)
    remat: bool = False

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_heads


def bert_large_config(**overrides) -> BertConfig:
    return dataclasses.replace(BertConfig(), **overrides)


def bert_tiny_config(**overrides) -> BertConfig:
    """Toy config for unit tests / CPU-mesh dryruns."""
    base = BertConfig(
        vocab_size=512, hidden_size=128, num_layers=2, num_heads=4,
        intermediate_size=256, max_position_embeddings=128,
        hidden_dropout=0.0, attention_dropout=0.0, dtype=jnp.float32,
    )
    return dataclasses.replace(base, **overrides)


class BertSelfAttention(nn.Module):
    """Fused QKV -> flash attention -> out-proj (multihead_attn analog)."""

    config: BertConfig

    @nn.compact
    def __call__(self, x, segment_ids, *, deterministic: bool, dropout_seed):
        cfg = self.config
        dt = resolve_compute_dtype(cfg.dtype)  # amp O1 seam
        e, h, d = cfg.hidden_size, cfg.num_heads, cfg.head_dim
        b, s, _ = x.shape
        init = nn.initializers.normal(0.02)
        qkv_w = self.param("qkv_weight", init, (e, 3 * e), cfg.param_dtype)
        qkv_b = self.param("qkv_bias", nn.initializers.zeros, (3 * e,),
                           cfg.param_dtype)
        out_w = self.param("out_weight", init, (e, e), cfg.param_dtype)
        out_b = self.param("out_bias", nn.initializers.zeros, (e,),
                           cfg.param_dtype)

        qkv = x @ qkv_w.astype(dt) + qkv_b.astype(dt)
        q, k, v = jnp.split(qkv, 3, axis=-1)

        def to_bhsd(t):
            return t.reshape(b, s, h, d).transpose(0, 2, 1, 3)

        rate = 0.0 if deterministic else cfg.attention_dropout
        ctx = flash_attention(
            to_bhsd(q), to_bhsd(k), to_bhsd(v), segment_ids=segment_ids,
            dropout_rate=rate, dropout_seed=dropout_seed,
        )
        ctx = ctx.transpose(0, 2, 1, 3).reshape(b, s, e)
        # out-proj stays in compute dtype; the bias add fuses into the GEMM
        return ctx @ out_w.astype(dt) + out_b.astype(dt)


class BertLayer(nn.Module):
    """Post-LN encoder layer (original BERT / standalone_bert ordering)."""

    config: BertConfig

    @nn.compact
    def __call__(self, x, segment_ids, deterministic: bool = True, *,
                 dropout_seed=0):
        # ``deterministic`` is positional(-able) so nn.remat can declare it
        # static (a traced bool would break the dropout-rate branch)
        cfg = self.config
        dt = resolve_compute_dtype(cfg.dtype)
        attn_out = BertSelfAttention(cfg, name="attention")(
            x, segment_ids, deterministic=deterministic,
            dropout_seed=dropout_seed)
        if not deterministic and cfg.hidden_dropout > 0.0:
            attn_out = nn.Dropout(cfg.hidden_dropout)(
                attn_out, deterministic=False)
        x = FusedLayerNorm(cfg.hidden_size, eps=cfg.layernorm_eps,
                           name="attention_norm")(x + attn_out)

        init = nn.initializers.normal(0.02)
        w1 = self.param("mlp_weight1", init,
                        (cfg.hidden_size, cfg.intermediate_size),
                        cfg.param_dtype)
        b1 = self.param("mlp_bias1", nn.initializers.zeros,
                        (cfg.intermediate_size,), cfg.param_dtype)
        w2 = self.param("mlp_weight2", init,
                        (cfg.intermediate_size, cfg.hidden_size),
                        cfg.param_dtype)
        b2 = self.param("mlp_bias2", nn.initializers.zeros,
                        (cfg.hidden_size,), cfg.param_dtype)
        hmid = jax.nn.gelu(x @ w1.astype(dt) + b1.astype(dt),
                           approximate=cfg.gelu_approximate)
        mlp_out = hmid @ w2.astype(dt) + b2.astype(dt)
        if not deterministic and cfg.hidden_dropout > 0.0:
            mlp_out = nn.Dropout(cfg.hidden_dropout)(
                mlp_out, deterministic=False)
        return FusedLayerNorm(cfg.hidden_size, eps=cfg.layernorm_eps,
                              name="mlp_norm")(x + mlp_out)


class BertForPreTraining(nn.Module):
    """Embeddings + encoder + MLM head + NSP head.

    ``__call__(input_ids, token_type_ids, attention_mask)`` returns
    ``(mlm_logits [B,S,V], nsp_logits [B,2])``. The MLM decoder is tied to the
    word-embedding table (standard BERT; standalone_bert does the same via
    Megatron's tied embeddings).

    ``masked_positions`` (optional, [B, K] int32): evaluate the MLM head
    only at those K positions (returns ``mlm_logits [B,K,V]``) — the
    reference pretraining harness's max_predictions_per_seq gather, which
    cuts the head's dense+decode GEMMs (the 2·e·v term that rivals a full
    encoder layer) to K/S of their all-positions cost. Pad rows with
    position 0 and label 0 (the loss's padding_idx drops them).
    """

    config: BertConfig

    @nn.compact
    def __call__(self, input_ids, token_type_ids=None, attention_mask=None,
                 *, deterministic: bool = True, dropout_seed=0,
                 masked_positions=None):
        cfg = self.config
        dt = resolve_compute_dtype(cfg.dtype)
        b, s = input_ids.shape
        init = nn.initializers.normal(0.02)

        word_emb = self.param("word_embeddings", init,
                              (cfg.vocab_size, cfg.hidden_size),
                              cfg.param_dtype)
        pos_emb = self.param("position_embeddings", init,
                             (cfg.max_position_embeddings, cfg.hidden_size),
                             cfg.param_dtype)
        type_emb = self.param("token_type_embeddings", init,
                              (cfg.type_vocab_size, cfg.hidden_size),
                              cfg.param_dtype)

        x = jnp.take(word_emb, input_ids, axis=0)
        x = x + pos_emb[None, :s, :]
        if token_type_ids is not None:
            x = x + jnp.take(type_emb, token_type_ids, axis=0)
        x = FusedLayerNorm(cfg.hidden_size, eps=cfg.layernorm_eps,
                           name="embedding_norm")(x)
        x = x.astype(dt)
        if not deterministic and cfg.hidden_dropout > 0.0:
            x = nn.Dropout(cfg.hidden_dropout)(x, deterministic=False)

        # padding mask -> kernel-native segment ids (reference fmha's
        # cu_seqlens semantics: pad keys are invisible to valid queries and
        # pad-position outputs are excluded from every loss). Cheaper than
        # the previous additive [B, 1, S, S]-broadcast bias: the kernel
        # loads two int rows per tile instead of a (bq, bk) f32 block, and
        # an all-ones mask costs only the comparisons.
        segment_ids = None
        if attention_mask is not None:
            segment_ids = attention_mask.astype(jnp.int32)

        layer_cls = (nn.remat(BertLayer, static_argnums=(3,)) if cfg.remat
                     else BertLayer)
        for i in range(cfg.num_layers):
            # decorrelate attention-dropout streams across (step, layer):
            # plain seed+i would reuse step s layer i+1's mask at step s+1
            # layer i (the counter-based keep-mask is a pure function of the
            # seed)
            layer_seed = (jnp.asarray(dropout_seed, jnp.int32)
                          * jnp.int32(1000003) + i)
            x = layer_cls(cfg, name=f"layer_{i}")(
                x, segment_ids, deterministic,
                dropout_seed=layer_seed)

        # MLM head: dense + gelu + LN + tied decode (BertLMPredictionHead)
        mlm_w = self.param("mlm_dense_weight", init,
                           (cfg.hidden_size, cfg.hidden_size), cfg.param_dtype)
        mlm_b = self.param("mlm_dense_bias", nn.initializers.zeros,
                           (cfg.hidden_size,), cfg.param_dtype)
        mlm_out_b = self.param("mlm_output_bias", nn.initializers.zeros,
                               (cfg.vocab_size,), cfg.param_dtype)
        x_head = x
        if masked_positions is not None:
            # [B, S, e] -> [B, K, e]: only predicted positions feed the head
            x_head = jnp.take_along_axis(
                x, masked_positions[..., None].astype(jnp.int32), axis=1)
        hmlm = jax.nn.gelu(x_head @ mlm_w.astype(dt) + mlm_b.astype(dt),
                           approximate=cfg.gelu_approximate)
        hmlm = FusedLayerNorm(cfg.hidden_size, eps=cfg.layernorm_eps,
                              name="mlm_norm")(hmlm).astype(dt)
        mlm_logits = hmlm @ word_emb.T.astype(dt) + mlm_out_b.astype(dt)

        # NSP head over the [CLS] (position 0) vector
        pool_w = self.param("pooler_weight", init,
                            (cfg.hidden_size, cfg.hidden_size), cfg.param_dtype)
        pool_b = self.param("pooler_bias", nn.initializers.zeros,
                            (cfg.hidden_size,), cfg.param_dtype)
        nsp_w = self.param("nsp_weight", init, (cfg.hidden_size, 2),
                           cfg.param_dtype)
        nsp_b = self.param("nsp_bias", nn.initializers.zeros, (2,),
                           cfg.param_dtype)
        pooled = jnp.tanh(x[:, 0, :] @ pool_w.astype(dt)
                          + pool_b.astype(dt))
        nsp_logits = pooled @ nsp_w.astype(dt) + nsp_b.astype(dt)
        return mlm_logits, nsp_logits


def bert_pretrain_loss(mlm_logits, nsp_logits, mlm_labels, nsp_labels):
    """MLM + NSP loss via the fused xentropy kernel.

    ``mlm_labels`` uses 0 (= [PAD]) for unpredicted positions — the fused
    kernel's ``padding_idx`` semantics zero those rows (reference:
    apex/contrib/xentropy label smoothing test uses the same convention).
    """
    v = mlm_logits.shape[-1]
    per_tok = softmax_cross_entropy(
        mlm_logits.reshape(-1, v).astype(jnp.float32),
        mlm_labels.reshape(-1), padding_idx=0)
    denom = jnp.maximum((mlm_labels.reshape(-1) != 0).sum(), 1)
    mlm_loss = per_tok.sum() / denom
    nsp_loss = softmax_cross_entropy(
        nsp_logits.astype(jnp.float32), nsp_labels, padding_idx=-1).mean()
    return mlm_loss + nsp_loss


# =============================================================================
# Parallelism: Megatron-style PartitionSpecs (SURVEY.md §2.4 TP column)
# =============================================================================

def param_partition_specs(params) -> Any:
    """PartitionSpec pytree: TP over the ``model`` axis, Megatron layout.

    Column-parallel (split output features): qkv_weight, mlp_weight1 —
    ColumnParallelLinear's sharding. Row-parallel (split input features):
    out_weight, mlp_weight2 — RowParallelLinear's. Vocab-parallel: word
    embeddings split over vocab (VocabParallelEmbedding). Everything else
    (norms, biases of row-parallel layers, pos/type embeddings) replicated.
    XLA GSPMD then inserts exactly the collectives the reference's
    mappings.py issues by hand.
    """

    def spec_for(path: str, x) -> P:
        if "qkv_weight" in path:
            return P(None, MODEL_AXIS)        # column: split 3*e outputs
        if "qkv_bias" in path:
            return P(MODEL_AXIS)
        if "mlp_weight1" in path:
            return P(None, MODEL_AXIS)        # column: split intermediate
        if "mlp_bias1" in path:
            return P(MODEL_AXIS)
        if "out_weight" in path:
            return P(MODEL_AXIS, None)        # row: split e inputs
        if "mlp_weight2" in path:
            return P(MODEL_AXIS, None)        # row: split intermediate inputs
        if "word_embeddings" in path:
            return P(MODEL_AXIS, None)        # vocab-parallel embedding
        return P()

    from apex_tpu.optimizers.common import path_name

    return jax.tree_util.tree_map_with_path(
        lambda p, x: spec_for(path_name(p), x), params)


def synthetic_batch(rng, cfg: BertConfig, batch_size: int, seq_len: int,
                    mlm_fraction: float = 0.15) -> Dict[str, jnp.ndarray]:
    """Random pretraining batch (the benchmark uses synthetic data, like the
    reference's tests/L1 synthetic-data mode).

    Emits BOTH label views of the same prediction set: the dense
    ``mlm_labels`` [B, S] (0 = unpredicted) for all-positions heads, and
    the reference harness's max_predictions_per_seq form —
    ``mlm_positions`` [B, K] + ``mlm_gathered_labels`` [B, K] — which
    ``make_pretrain_step`` feeds to the model's gathered MLM head (K ~
    0.15*S rounded up to a lane-friendly multiple of 8)."""
    ids = rng.integers(4, cfg.vocab_size, size=(batch_size, seq_len))
    k = min(seq_len, max(8, -(-int(seq_len * mlm_fraction) // 8) * 8))
    # k distinct positions per row, vectorized (uniform without replacement)
    positions = np.sort(
        np.argsort(rng.random((batch_size, seq_len)), axis=1)[:, :k], axis=1)
    gathered = np.take_along_axis(ids, positions, axis=1)
    dense = np.zeros_like(ids)
    np.put_along_axis(dense, positions, gathered, axis=1)
    return {
        "input_ids": jnp.asarray(ids, jnp.int32),
        "token_type_ids": jnp.asarray(
            rng.integers(0, cfg.type_vocab_size, size=(batch_size, seq_len)),
            jnp.int32),
        "attention_mask": jnp.ones((batch_size, seq_len), jnp.int32),
        "mlm_labels": jnp.asarray(dense, jnp.int32),
        "mlm_positions": jnp.asarray(positions, jnp.int32),
        "mlm_gathered_labels": jnp.asarray(gathered, jnp.int32),
        "nsp_labels": jnp.asarray(
            rng.integers(0, 2, size=(batch_size,)), jnp.int32),
    }


def make_pretrain_step(model: BertForPreTraining, mesh=None,
                       partition_params: bool = False):
    """Build the jitted grad step: (params, batch, seed) -> (loss, grads).

    DP comes from sharding the batch over ``data``; TP (optional) from
    partitioning params over ``model`` via ``param_partition_specs``. The
    optimizer step (FusedLAMB.step) is its own jitted+donated call — together
    they are the full training step of BASELINE config #2.
    """

    def loss_fn(params, batch, seed):
        # the gathered head (max_predictions_per_seq) when the batch carries
        # positions: the MLM dense+decode run at K ~ 0.15*S positions
        positions = batch.get("mlm_positions")
        mlm_logits, nsp_logits = model.apply(
            {"params": params},
            batch["input_ids"], batch["token_type_ids"],
            batch["attention_mask"],
            deterministic=False, dropout_seed=seed,
            masked_positions=positions,
            rngs={"dropout": jax.random.fold_in(jax.random.PRNGKey(0), seed)},
        )
        labels = (batch["mlm_gathered_labels"] if positions is not None
                  else batch["mlm_labels"])
        return bert_pretrain_loss(mlm_logits, nsp_logits,
                                  labels, batch["nsp_labels"])

    grad_fn = jax.value_and_grad(loss_fn)

    if mesh is None:
        return jax.jit(grad_fn)

    from jax.sharding import NamedSharding

    batch_spec = {
        "input_ids": P(DATA_AXIS, CONTEXT_AXIS),
        "token_type_ids": P(DATA_AXIS, CONTEXT_AXIS),
        "attention_mask": P(DATA_AXIS, CONTEXT_AXIS),
        "mlm_labels": P(DATA_AXIS, CONTEXT_AXIS),
        # gathered view: positions index the FULL sequence, so they stay
        # unsharded over context (the gather crosses context shards; the
        # mesh path only shards them over data)
        "mlm_positions": P(DATA_AXIS),
        "mlm_gathered_labels": P(DATA_AXIS),
        "nsp_labels": P(DATA_AXIS),
    }
    batch_sh = jax.tree.map(lambda s: NamedSharding(mesh, s), batch_spec,
                            is_leaf=lambda x: isinstance(x, P))

    def with_param_sharding(params):
        specs = (param_partition_specs(params) if partition_params
                 else jax.tree.map(lambda _: P(), params))
        return jax.tree.map(
            lambda x, s: jax.device_put(x, NamedSharding(mesh, s)),
            params, specs)

    step = jax.jit(grad_fn, in_shardings=(None, batch_sh, None))
    return step, with_param_sharding, batch_sh
