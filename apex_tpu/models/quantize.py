"""fp checkpoint -> int8-serving param tree (models with quantize_int8).

Beyond reference (apex has no quantization story). The quantized models
(``quantize_int8=True`` on ``GPTConfig``/``LlamaConfig``/``T5Config``)
expect each block linear's ``weight`` as int8 plus a per-output-channel
``scale`` (transformer/tensor_parallel/layers.py); this module produces
that tree from a TRAINED fp tree — post-training quantization, the
ordinary serving flow:

    fp_vars = model_fp.init(...)          # or an HF-converted checkpoint
    qmodel = GPTModel(dataclasses.replace(cfg, quantize_int8=True))
    qparams = quantize_model_params(qmodel, fp_vars, example_ids)
    generate(qmodel, {"params": qparams}, prompt, ...)

Leaves the target expects in fp (embeddings, norms, biases, heads) pass
through unchanged.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from apex_tpu.ops.quant import quantize_weight


def quantize_params_like(target_shapes, params_fp):
    """Build the quantized tree: wherever ``target_shapes`` holds an int8
    ``weight`` with a sibling ``scale``, quantize the fp source weight
    per-output-channel; everything else passes through."""
    def walk(tgt, src):
        if isinstance(tgt, dict):
            out = {}
            wants_q = ("weight" in tgt and "scale" in tgt
                       and tgt["weight"].dtype == jnp.int8)
            for k in tgt:
                if wants_q and k == "weight":
                    out["weight"], out["scale"] = quantize_weight(
                        src["weight"])
                elif wants_q and k == "scale":
                    continue  # produced with the weight
                else:
                    out[k] = walk(tgt[k], src[k])
            return out
        return src

    return walk(target_shapes, params_fp)


def quantize_model_params(qmodel, fp_variables, *example_args):
    """fp ``{"params": ...}`` (trained or HF-converted) -> the param tree
    of ``qmodel`` (a model constructed with ``quantize_int8=True``)."""
    target = jax.eval_shape(
        lambda: qmodel.init(jax.random.PRNGKey(0), *example_args))["params"]
    return quantize_params_like(target, fp_variables["params"])


def assert_quantized_loaded(params) -> None:
    """Fail loud if a quantized tree still holds its ``init()`` placeholders.

    A model built with ``quantize_int8=True`` init()s every block linear to
    all-zero int8 weights (real values come from ``quantize_model_params``
    on a trained checkpoint) — serving such a tree silently produces zero
    logits from every block linear (ADVICE r4). Call this before serving;
    it raises ``ValueError`` naming the first all-zero int8 weight."""
    leaves = jax.tree_util.tree_flatten_with_path(params)[0]
    from apex_tpu.optimizers.common import path_name

    checked = 0
    for path, leaf in leaves:
        if getattr(leaf, "dtype", None) == jnp.int8:
            checked += 1
            if not bool(jnp.any(leaf != 0)):
                raise ValueError(
                    f"int8 weight {path_name(path)!r} is all zeros — this "
                    "tree looks like init() placeholders; load real values "
                    "with quantize_model_params() before serving")
    if checked == 0:
        raise ValueError(
            "no int8 leaves found — was this model built with "
            "quantize_int8=True?")
