"""fp checkpoint -> quantized-serving param tree (per-layer precision).

Beyond reference (apex has no quantization story; PAPER.md's ``apex.amp``
opt levels are the per-layer-class precedent). A model built with a
``WeightPrecisionPolicy`` (``ops/quant.py``) — or the back-compat
``quantize_int8=True`` alias — expects each block linear's ``weight``
narrow (int8 / fp8 e4m3 per-channel, or int4 packed nibbles) with a
sibling ``scale``; this module produces that tree from a TRAINED fp tree
— post-training quantization, the ordinary serving flow:

    fp_vars = model_fp.init(...)          # or an HF-converted checkpoint
    qmodel = GPTModel(dataclasses.replace(
        cfg, weight_policy=WeightPrecisionPolicy("int4")))
    qparams = quantize_model_params(qmodel, fp_vars, example_ids)
    generate(qmodel, {"params": qparams}, prompt, ...)

Leaves the target expects in fp (embeddings, norms, biases, heads) pass
through unchanged.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from apex_tpu.ops.quant import (WeightPrecisionPolicy, quantize_weight,
                                quantize_weight_fp8, quantize_weight_int4)

__all__ = ["WeightPrecisionPolicy", "quantize_params_like",
           "quantize_model_params", "assert_quantized_loaded"]

_FP8 = getattr(jnp, "float8_e4m3fn", None)


def _target_kind(tgt):
    """The storage kind a (weight, scale) target pair asks for, by its
    weight dtype: int8 / fp8 per-channel, uint8 = packed int4 nibbles."""
    dt = tgt["weight"].dtype
    if dt == jnp.int8:
        return "int8"
    if _FP8 is not None and dt == _FP8:
        return "fp8"
    if dt == jnp.uint8:
        return "int4"
    return None


def quantize_params_like(target_shapes, params_fp):
    """Build the quantized tree: wherever ``target_shapes`` holds a
    narrow ``weight`` with a sibling ``scale``, quantize the fp source
    weight to that kind (the int4 group size is read off the target
    scale's group axis); everything else passes through untouched."""
    def walk(tgt, src):
        if isinstance(tgt, dict):
            out = {}
            kind = ("weight" in tgt and "scale" in tgt
                    and _target_kind(tgt)) or None
            for k in tgt:
                if kind and k == "weight":
                    w = src["weight"]
                    if kind == "int8":
                        out["weight"], out["scale"] = quantize_weight(w)
                    elif kind == "fp8":
                        out["weight"], out["scale"] = quantize_weight_fp8(w)
                    else:
                        gs = w.shape[1] // tgt["scale"].shape[0]
                        out["weight"], out["scale"] = quantize_weight_int4(
                            w, group_size=gs)
                elif kind and k == "scale":
                    continue  # produced with the weight
                else:
                    out[k] = walk(tgt[k], src[k])
            return out
        return src

    return walk(target_shapes, params_fp)


def quantize_model_params(qmodel, fp_variables, *example_args):
    """fp ``{"params": ...}`` (trained or HF-converted) -> the param tree
    of ``qmodel`` (a model constructed with a weight policy /
    ``quantize_int8=True``)."""
    target = jax.eval_shape(
        lambda: qmodel.init(jax.random.PRNGKey(0), *example_args))["params"]
    return quantize_params_like(target, fp_variables["params"])


def assert_quantized_loaded(params) -> None:
    """Fail loud if a quantized tree still holds its ``init()`` placeholders.

    A quantized model init()s every block linear to all-zero narrow
    weights (real values come from ``quantize_model_params`` on a trained
    checkpoint) — serving such a tree silently produces garbage from
    every block linear (ADVICE r4). Call this before serving; it raises
    ``ValueError`` naming the first all-zero quantized weight."""
    leaves = jax.tree_util.tree_flatten_with_path(params)[0]
    from apex_tpu.optimizers.common import path_name

    narrow = {jnp.dtype(jnp.int8), jnp.dtype(jnp.uint8)}
    if _FP8 is not None:
        narrow.add(jnp.dtype(_FP8))
    checked = 0
    for path, leaf in leaves:
        dt = getattr(leaf, "dtype", None)
        if dt is not None and jnp.dtype(dt) in narrow:
            checked += 1
            if not bool(jnp.any(leaf.astype(jnp.float32) != 0)):
                raise ValueError(
                    f"quantized weight {path_name(path)!r} is all zeros — "
                    "this tree looks like init() placeholders; load real "
                    "values with quantize_model_params() before serving")
    if checked == 0:
        raise ValueError(
            "no int8/fp8/int4 leaves found — was this model built with a "
            "weight policy (or quantize_int8=True)?")
