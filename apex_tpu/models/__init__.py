"""Model zoo used by benchmarks, examples, and parity tests.

Reference: the reference ships no model zoo proper — its models live in
``examples/`` (ResNet-50 ImageNet: examples/imagenet/main_amp.py) and in
test-only vendored Megatron models (apex/transformer/testing/standalone_bert.py,
standalone_gpt.py). Here the same roles are played by first-class modules so the
benchmarks (BASELINE.md configs) are reproducible from the library itself.
"""

from apex_tpu.models import bert  # noqa: F401
from apex_tpu.models import gpt  # noqa: F401
from apex_tpu.models.gpt import (  # noqa: F401
    GPTConfig,
    GPTModel,
    gpt2_small_config,
    gpt_loss,
    gpt_tiny_config,
    lm_token_loss,
)
from apex_tpu.models import generation  # noqa: F401
from apex_tpu.models.generation import (  # noqa: F401
    generate,
    generate_beam,
    init_cache,
    speculative_generate,
)
from apex_tpu.models import hf_convert  # noqa: F401
from apex_tpu.models import quantize  # noqa: F401
from apex_tpu.models.quantize import (  # noqa: F401
    assert_quantized_loaded,
    quantize_model_params,
)
from apex_tpu.models import llama  # noqa: F401
from apex_tpu.models.hf_convert import (  # noqa: F401
    bert_config_from_hf,
    bert_params_from_hf,
    gpt2_config_from_hf,
    gpt2_params_from_hf,
    llama_config_from_hf,
    llama_params_from_hf,
    t5_config_from_hf,
    t5_params_from_hf,
)
from apex_tpu.models.llama import (  # noqa: F401
    LlamaConfig,
    LlamaModel,
    llama_loss,
    llama_tiny_config,
)
from apex_tpu.models import t5  # noqa: F401
from apex_tpu.models.t5 import (  # noqa: F401
    T5Config,
    T5Model,
    t5_beam_search,
    t5_generate,
    t5_loss,
    t5_tiny_config,
)
from apex_tpu.models.bert import (  # noqa: F401
    BertConfig,
    BertForPreTraining,
    bert_large_config,
    bert_pretrain_loss,
    bert_tiny_config,
    make_pretrain_step,
    param_partition_specs,
    synthetic_batch,
)
