"""Llama-family decoder (RMSNorm + RoPE + SwiGLU + GQA), tensor-parallel.

No reference analog (apex ships no models; its test GPT is the vendored
Megatron driver) — this is the second first-class model family, exercising
the components the BERT/GPT flagships don't: ``FusedRMSNorm``
(normalization/fused_layer_norm.py), the cached-RoPE functional
(transformer/functional/fused_rope.py, reference
fused_rotary_positional_embedding), grouped-query attention on the flash
kernel, and a SwiGLU MLP over the Megatron TP linears.

Same parallel contract as GPTModel (models/gpt.py): runs inside shard_map
with ``model`` bound for TP (heads AND kv-heads divide over the axis),
``context_parallel`` opts into ring attention with the sequence sharded
over ``context``.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax import lax

from apex_tpu.amp.policy import resolve_compute_dtype
from apex_tpu.mesh import CONTEXT_AXIS, MODEL_AXIS
from apex_tpu.models.generation import (advance_cache, cached_attention,
                                        cached_attention_rolling,
                                        check_chunk_bounds, is_paged,
                                        is_static_prefill, layer_cache,
                                        update_layer_cache,
                                        update_layer_cache_rolling,
                                        update_paged_layer_cache)
from apex_tpu.models.gpt import lm_token_loss
from apex_tpu.normalization import FusedRMSNorm
from apex_tpu.ops import (flash_attention, ring_attention,
                          ring_attention_zigzag)
from apex_tpu.transformer.functional.fused_rope import (
    fused_apply_rotary_pos_emb_cached,
)
from apex_tpu.transformer.tensor_parallel import (
    ColumnParallelLinear,
    RowParallelLinear,
    VocabParallelEmbedding,
)
from apex_tpu.transformer.tensor_parallel.mappings import (
    axis_is_bound as _axis_bound,
)
from apex_tpu.transformer.utils import divide


@dataclasses.dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 32000
    hidden_size: int = 4096
    intermediate_size: int = 11008       # SwiGLU inner width
    num_layers: int = 32
    num_heads: int = 32
    num_kv_heads: int = 32               # < num_heads => GQA
    max_position_embeddings: int = 4096
    rope_theta: float = 10000.0
    rms_eps: float = 1e-5
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    tensor_parallel_size: int = 1
    context_parallel: bool = False       # same opt-in as GPTConfig
    # zigzag CP layout (causal load balancing): each device holds one early
    # + one late half-chunk (ops/ring_attention.py to_zigzag); the CALLER
    # feeds input_ids/labels already zigzag-permuted along the sequence.
    # RoPE positions and attention follow the layout automatically.
    context_parallel_zigzag: bool = False
    tie_word_embeddings: bool = False
    # Mistral-style sliding-window attention: band-restricted in the flash
    # kernel (O(S*window) compute+DMA); under context_parallel the ring is
    # statically shortened to the chunks the band reaches (fewer ppermutes).
    sliding_window: Optional[int] = None
    # rolling KV cache for decode (requires sliding_window): a ring of
    # ``window`` slots instead of a full-length buffer — O(window) HBM for
    # arbitrarily long generation (models/generation.py). Single-token
    # steps only after prefill (speculative/chunked continuation raise).
    rolling_cache: bool = False
    # --- mixture-of-experts (Mixtral family = GQA + window + MoE) ---------
    # Same contract as GPTConfig: num_experts > 0 routes every
    # moe_layer_freq-th block's MLP through MoEMLP — with SWIGLU experts
    # (Mixtral's expert FFN); expert_parallel opts into EP over ``data``.
    num_experts: int = 0
    moe_layer_freq: int = 2
    moe_k: int = 2
    moe_capacity_factor: float = 1.25
    moe_aux_loss_coeff: float = 1e-2
    moe_z_loss_coeff: float = 0.0
    expert_parallel: bool = False
    # quantized weight streaming for the block linears (same contract as
    # GPTConfig: quantize_int8 = int8-everywhere alias, weight_policy =
    # WeightPrecisionPolicy for int8/fp8/int4-grouped;
    # lm_head/embedding/norms stay fp)
    quantize_int8: bool = False
    weight_policy: Any = None            # Optional[WeightPrecisionPolicy]
    # activation rematerialization per decoder block (same as GPTConfig)
    remat: bool = False

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_heads

    def weight_quant(self):
        """Resolved ``WeightPrecisionPolicy`` (or None) — same seam as
        ``GPTConfig.weight_quant``."""
        from apex_tpu.ops.quant import WeightPrecisionPolicy

        return WeightPrecisionPolicy.resolve(self.weight_policy,
                                             self.quantize_int8)


def llama_tiny_config(**overrides) -> LlamaConfig:
    base = LlamaConfig(vocab_size=128, hidden_size=64, intermediate_size=176,
                       num_layers=2, num_heads=4, num_kv_heads=2,
                       max_position_embeddings=128, dtype=jnp.float32)
    return dataclasses.replace(base, **overrides)


def _rope_freqs(cfg: LlamaConfig, pos):
    """cos/sin rows for a vector of absolute positions — the ONE place
    the RoPE frequency formula lives (contiguous offsets and the paged
    per-slot gather both shape these rows). Returns ``(n, head_dim)``
    pairs in the fused_rope rotate-half convention
    ([first-half | second-half])."""
    d = cfg.head_dim
    inv = 1.0 / (cfg.rope_theta
                 ** (jnp.arange(0, d, 2, dtype=jnp.float32) / d))
    ang = pos.astype(jnp.float32)[:, None] * inv[None, :]       # (n, d/2)
    freqs = jnp.concatenate([ang, ang], axis=-1)                # (n, d)
    return jnp.cos(freqs), jnp.sin(freqs)


def _rope_cos_sin(cfg: LlamaConfig, s: int, offset):
    """cos/sin tables for local positions [offset, offset+s), shape
    (s, 1, 1, head_dim) — the cached-RoPE layout ([sq, b, np, hn])."""
    cos, sin = _rope_freqs(cfg, jnp.arange(s, dtype=jnp.int32) + offset)
    return cos[:, None, None, :], sin[:, None, None, :]


class LlamaDecoderBlock(nn.Module):
    """Pre-RMSNorm block: attn (RoPE + GQA flash) -> res -> SwiGLU -> res.

    ``config.num_experts > 0`` + this block selected by ``moe_layer_freq``
    routes the MLP through MoEMLP with SwiGLU experts (Mixtral); the aux
    loss is sown into ``intermediates`` (collected by ``llama_loss``)."""

    config: LlamaConfig
    layer_idx: int = 0

    def _is_moe_layer(self) -> bool:
        from apex_tpu.transformer.moe import moe_layer_selected

        return moe_layer_selected(self.config, self.layer_idx)

    @nn.compact
    def __call__(self, x, cos_, sin_, cache=None):
        cfg = self.config
        dt = resolve_compute_dtype(cfg.dtype)
        tp = cfg.tensor_parallel_size
        e = cfg.hidden_size
        h_local = divide(cfg.num_heads, tp)
        kv_local = divide(cfg.num_kv_heads, tp)
        d = cfg.head_dim
        b, s, _ = x.shape

        pol = cfg.weight_quant()
        qmode = pol.linears if pol else False
        qgs = pol.group_size if pol else 128

        h = FusedRMSNorm(e, eps=cfg.rms_eps, name="input_norm")(x)
        h = h.astype(dt)
        q = ColumnParallelLinear(
            e, cfg.num_heads * d, bias=False, gather_output=False,
            world_size=tp, params_dtype=cfg.param_dtype,
            quantize=qmode, quantize_group_size=qgs, name="q_proj")(h)
        kv = ColumnParallelLinear(
            e, 2 * cfg.num_kv_heads * d, bias=False, gather_output=False,
            world_size=tp, params_dtype=cfg.param_dtype,
            quantize=qmode, quantize_group_size=qgs, name="kv_proj")(h)
        k, v = jnp.split(kv, 2, axis=-1)

        def to_shd(t, nh):  # (b, s, nh*d) -> (s, b, nh, d): rope layout
            return t.reshape(b, s, nh, d).transpose(1, 0, 2, 3)

        q = fused_apply_rotary_pos_emb_cached(to_shd(q, h_local), cos_, sin_)
        k = fused_apply_rotary_pos_emb_cached(to_shd(k, kv_local), cos_, sin_)

        def to_bhsd(t):  # (s, b, nh, d) -> (b, nh, s, d): kernel layout
            return t.transpose(1, 2, 0, 3)

        q, k = to_bhsd(q), to_bhsd(k)
        v = v.reshape(b, s, kv_local, d).transpose(0, 2, 1, 3)
        # GQA: both the flash kernel and the ring index kv heads natively
        # (h // rep block index maps) — no repeated K/V in HBM, and under CP
        # the ppermute payload stays rep-times smaller. divide() raises on
        # non-divisible ratios at the source.
        divide(h_local, kv_local)

        if cache is not None and is_paged(cache):
            # paged serving decode (apex_tpu/serving): write this token's
            # RoPE'd K (and V) into the slot's current page, then
            # gather-attend over the block table with the Pallas paged
            # kernel — same wiring as gpt.py, with the model handing in
            # per-slot cos/sin tables for each slot's own position
            from apex_tpu.ops.paged_attention import paged_attention

            cache = update_paged_layer_cache(cache, k, v)
            # sliding_window bands the paged kernel to the exact
            # rolling-cache attention set (per query position for s>1);
            # the serving engine additionally DROPS pages that fall fully
            # below the band from the block table
            # (kv_pool.drop_slot_pages) — O(window) live pages per slot
            # for arbitrarily long generation, the paged analog of the
            # rolling ring buffer
            ctx = paged_attention(q, cache["k_pages"], cache["v_pages"],
                                  cache["block_tables"], cache["len"] + s,
                                  window=cfg.sliding_window,
                                  k_scales=cache.get("k_scales"),
                                  v_scales=cache.get("v_scales"))
        elif cache is not None:
            # incremental decoding: append K/V at the cache offset; a
            # trace-time-provable prefill rides the training flash kernel,
            # decode steps the absolute-position (windowed) masked product.
            # rolling_cache swaps in the O(window) ring-buffer variants
            prefill = is_static_prefill(cache, s)
            update_fn = update_layer_cache_rolling if cfg.rolling_cache \
                else update_layer_cache
            cache = update_fn(cache, k, v)
            if prefill:
                ctx = flash_attention(q, k, v, causal=True,
                                      window=cfg.sliding_window)
            elif cfg.rolling_cache:
                ctx = cached_attention_rolling(q, cache,
                                               window=cfg.sliding_window)
            else:
                ctx = cached_attention(q, cache, window=cfg.sliding_window)
        elif cfg.context_parallel and _axis_bound(CONTEXT_AXIS):
            if cfg.context_parallel_zigzag:
                # causal load-balanced layout; windows compose via the
                # static/dynamic-offset banding (ops/ring_attention.py)
                ctx = ring_attention_zigzag(q, k, v, axis_name=CONTEXT_AXIS,
                                            window=cfg.sliding_window)
            else:
                # window-aware ring: statically shortened to the chunks the
                # band reaches (ops/ring_attention.py)
                ctx = ring_attention(q, k, v, axis_name=CONTEXT_AXIS,
                                     causal=True, window=cfg.sliding_window)
        else:
            ctx = flash_attention(q, k, v, causal=True,
                                  window=cfg.sliding_window)
        ctx = ctx.transpose(0, 2, 1, 3).reshape(b, s, h_local * d)
        attn_out = RowParallelLinear(
            e, e, bias=False, input_is_parallel=True, world_size=tp,
            params_dtype=cfg.param_dtype, quantize=qmode,
            quantize_group_size=qgs, name="o_proj")(ctx)
        x = x + attn_out.astype(x.dtype)

        h = FusedRMSNorm(e, eps=cfg.rms_eps, name="post_norm")(x)
        h = h.astype(dt)
        if self._is_moe_layer():
            from apex_tpu.transformer.moe import make_moe_mlp

            # Mixtral expert FFN: swiglu, bias-free
            mlp_out, aux = make_moe_mlp(
                cfg, e, cfg.intermediate_size, "swiglu")(h)
            self.sow("intermediates", "moe_aux", aux.total)
        else:
            # gate+up fused into ONE column-parallel GEMM (same pattern as
            # kv_proj): one weight-load pass over h instead of two; local
            # layout is [gate_r | up_r]
            gate_up = ColumnParallelLinear(
                e, 2 * cfg.intermediate_size, bias=False,
                gather_output=False, world_size=tp,
                params_dtype=cfg.param_dtype, quantize=qmode,
                quantize_group_size=qgs, name="gate_up_proj")(h)
            gate, up = jnp.split(gate_up, 2, axis=-1)
            mlp_out = RowParallelLinear(
                cfg.intermediate_size, e, bias=False, input_is_parallel=True,
                world_size=tp, params_dtype=cfg.param_dtype,
                quantize=qmode, quantize_group_size=qgs,
                name="down_proj")(jax.nn.silu(gate) * up)
        out = x + mlp_out.astype(x.dtype)
        return out if cache is None else (out, cache)


class LlamaModel(nn.Module):
    """Decoder-only LM -> vocab-PARALLEL logits [B, S, vocab/tp] (feed to
    ``vocab_parallel_cross_entropy``). Untied LM head by default (Llama
    convention); ``tie_word_embeddings=True`` uses the embedding transpose."""

    config: LlamaConfig

    @nn.compact
    def __call__(self, input_ids, cache=None):
        cfg = self.config
        dt = resolve_compute_dtype(cfg.dtype)
        b, s = input_ids.shape
        if cfg.weight_quant() and cfg.num_experts > 0:
            raise NotImplementedError(
                "weight quantization (quantize_int8/weight_policy) does "
                "not cover MoE expert weights; the combination would "
                "silently serve fp experts")
        emb = VocabParallelEmbedding(
            cfg.vocab_size, cfg.hidden_size,
            world_size=cfg.tensor_parallel_size,
            params_dtype=cfg.param_dtype, name="embed_tokens")
        x = emb(input_ids).astype(dt)

        if cache is not None:
            # incremental decoding (models/generation.py): RoPE tables for
            # the absolute positions [len, len+s); blocks append K/V
            if cfg.context_parallel:
                raise ValueError(
                    "incremental decoding does not compose with context "
                    "parallelism; decode on a dp/tp mesh instead")

            if is_paged(cache):
                # paged serving decode: an s-token block per SLOT, each
                # slot at its own absolute positions [len, len+s) —
                # per-slot RoPE tables gather by the length vector (the
                # paged analog of gpt.py's per-slot position-embedding
                # gather; the scheduler guards the position cap, idle
                # slots sit at 0)
                if cfg.rolling_cache:
                    raise NotImplementedError(
                        "rolling_cache (ring buffer) does not compose "
                        "with the paged pool — pages already bound HBM")
                pos = jnp.clip(
                    cache["len"][:, None]
                    + jnp.arange(s, dtype=jnp.int32)[None, :],
                    0, cfg.max_position_embeddings - 1)     # (slots, s)
                cos, sin = _rope_freqs(cfg, pos.reshape(-1))
                # rope layout [sq, b, np=1, hn]: per-slot tables ride
                # the batch axis and broadcast over heads
                cos_ = cos.reshape(b, s, -1).transpose(1, 0, 2)[:, :, None, :]
                sin_ = sin.reshape(b, s, -1).transpose(1, 0, 2)[:, :, None, :]
            else:
                if cfg.rolling_cache and not cfg.sliding_window:
                    raise ValueError("rolling_cache requires sliding_window")
                t0 = check_chunk_bounds(cache, s,
                                        cfg.max_position_embeddings,
                                        rolling=cfg.rolling_cache)
                cos_, sin_ = _rope_cos_sin(cfg, s, t0)
        else:
            cp = (lax.axis_size(CONTEXT_AXIS)
                  if cfg.context_parallel and _axis_bound(CONTEXT_AXIS) else 1)
            if cp * s > cfg.max_position_embeddings:
                # RoPE would silently extrapolate past the trained range;
                # enforce uniformly (CP and single-device alike)
                raise ValueError(
                    f"global sequence cp*s = {cp}*{s} exceeds "
                    f"max_position_embeddings={cfg.max_position_embeddings}")
            if cp > 1 and cfg.context_parallel_zigzag:
                # zigzag slice = global chunks (i, 2cp-1-i): RoPE positions
                # follow the layout, one table per half-chunk
                if s % 2:
                    raise ValueError("zigzag CP needs an even local sequence")
                s_h = s // 2
                i = lax.axis_index(CONTEXT_AXIS)
                cos_e, sin_e = _rope_cos_sin(cfg, s_h, i * s_h)
                cos_l, sin_l = _rope_cos_sin(cfg, s_h, (2 * cp - 1 - i) * s_h)
                cos_ = jnp.concatenate([cos_e, cos_l], axis=0)
                sin_ = jnp.concatenate([sin_e, sin_l], axis=0)
            else:
                offset = lax.axis_index(CONTEXT_AXIS) * s if cp > 1 else 0
                cos_, sin_ = _rope_cos_sin(cfg, s, offset)

        block_cls = nn.remat(LlamaDecoderBlock) if cfg.remat and cache is None \
            else LlamaDecoderBlock
        new_layers = []
        for i in range(cfg.num_layers):
            blk = block_cls(cfg, layer_idx=i, name=f"layer_{i}")
            if cache is None:
                x = blk(x, cos_, sin_)
            else:

                x, lc = blk(x, cos_, sin_, cache=layer_cache(cache, i))
                new_layers.append(lc)
        x = FusedRMSNorm(cfg.hidden_size, eps=cfg.rms_eps, name="final_norm")(x)
        x = x.astype(dt)
        if cfg.tie_word_embeddings:
            logits = emb.attend(x)
        else:
            logits = ColumnParallelLinear(
                cfg.hidden_size, cfg.vocab_size, bias=False,
                gather_output=False, world_size=cfg.tensor_parallel_size,
                params_dtype=cfg.param_dtype, name="lm_head")(x)
        if cache is None:
            return logits

        return logits, advance_cache(cache, new_layers, s)


def llama_loss(model: LlamaModel, variables, input_ids, labels,
               axis_name: str = MODEL_AXIS):
    """Mean next-token loss from vocab-parallel logits (shared LM tail,
    + sown MoE aux losses for Mixtral-style configs)."""
    moe_aux = None
    if model.config.num_experts > 0:
        from apex_tpu.transformer.moe import collect_sown_aux

        logits, inter = model.apply(variables, input_ids,
                                    mutable=["intermediates"])
        moe_aux = collect_sown_aux(inter)
    else:
        logits = model.apply(variables, input_ids)
    return lm_token_loss(logits, labels, axis_name=axis_name,
                         context_parallel=model.config.context_parallel,
                         extra=moe_aux)
