"""T5-family encoder-decoder (RMSNorm, relative-position bias, bias-free
linears, unscaled attention), tensor-parallel.

No reference analog (apex ships no models) — the third first-class family,
and the first ENCODER-DECODER: it exercises the components the decoder-only
families don't: non-causal flash attention, cross-attention through the
flash kernel's separate kv operands (the `contrib.multihead_attn` Encdec
role in a full model), the kernel's ADDITIVE BIAS slot carrying T5's
bucketed relative-position bias (reference analog of that slot:
fmha/fast_multihead_attn additive masks), and encoder-KV caching at decode
time.

T5 specifics implemented: pre-RMSNorm everywhere, NO attention scaling
(T5 folds 1/sqrt(d) into init; ``scale=1.0`` on every kernel call),
bias-free linears, a SHARED relative-position bias table (one embedding,
computed once per forward, added in every self-attention layer; none in
cross-attention), and a relu or gated-gelu (v1.1) FFN.

Parallel contract matches GPT/Llama: Column/RowParallel linears inside
shard_map with ``model`` bound divide heads and FFN; the vocab-parallel
LM head feeds ``lm_token_loss``. The relative-bias table is replicated
and sliced to the local head shard by rank.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax import lax

from apex_tpu.amp.policy import resolve_compute_dtype
from apex_tpu.mesh import MODEL_AXIS
from apex_tpu.models.generation import (cached_attention, is_static_prefill,
                                        update_layer_cache)
from apex_tpu.models.gpt import lm_token_loss
from apex_tpu.normalization import FusedRMSNorm
from apex_tpu.ops import flash_attention
from apex_tpu.transformer.tensor_parallel import (
    ColumnParallelLinear,
    RowParallelLinear,
    VocabParallelEmbedding,
)
from apex_tpu.transformer.tensor_parallel.mappings import (
    axis_is_bound as _axis_bound,
)
from apex_tpu.transformer.utils import divide


@dataclasses.dataclass(frozen=True)
class T5Config:
    vocab_size: int = 32128
    d_model: int = 512
    d_ff: int = 2048
    num_layers: int = 6                  # encoder AND decoder depth
    num_heads: int = 8
    head_dim: int = 64                   # T5 decouples d_kv from d_model
    relative_attention_num_buckets: int = 32
    relative_attention_max_distance: int = 128
    rms_eps: float = 1e-6
    ff_act: str = "relu"                 # "relu" (v1.0) | "gated-gelu" (v1.1)
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    tensor_parallel_size: int = 1
    decoder_start_token_id: int = 0      # T5 convention: pad id starts decode
    # v1.0 ties the LM head to the shared embedding with the d_model^-0.5
    # rescale; v1.1 (gated-gelu) unties it and drops the rescale
    tie_word_embeddings: bool = True
    # int8 W8A8 serving for the block linears (same contract as
    # GPTConfig.quantize_int8; embeddings/rel-bias/head stay fp)
    quantize_int8: bool = False
    # practical cap for the decode cache/bias tables (T5's rel-bias has no
    # hard limit; this bounds the static decode buffers)
    max_position_embeddings: int = 512


def t5_tiny_config(**overrides) -> T5Config:
    base = T5Config(vocab_size=128, d_model=64, d_ff=128, num_layers=2,
                    num_heads=4, head_dim=16, max_position_embeddings=128,
                    dtype=jnp.float32)
    return dataclasses.replace(base, **overrides)


def relative_position_bucket(rel, *, bidirectional: bool, num_buckets: int,
                             max_distance: int):
    """T5's log-binned bucket of ``rel = k_pos - q_pos`` (the HF/mesh-tf
    formula): half the buckets exact, half log-spaced up to max_distance."""
    ret = jnp.zeros_like(rel)
    if bidirectional:
        num_buckets //= 2
        ret = ret + (rel > 0).astype(rel.dtype) * num_buckets
        n = jnp.abs(rel)
    else:
        n = jnp.maximum(-rel, 0)         # causal: only the past is bucketed
    max_exact = num_buckets // 2
    is_small = n < max_exact
    # guard log(0); masked to the exact branch anyway
    val_large = max_exact + (
        jnp.log(jnp.maximum(n, 1).astype(jnp.float32) / max_exact)
        / math.log(max_distance / max_exact)
        * (num_buckets - max_exact)).astype(rel.dtype)
    val_large = jnp.minimum(val_large, num_buckets - 1)
    return ret + jnp.where(is_small, n, val_large)


class T5RelativeBias(nn.Module):
    """The shared bias table: (num_buckets, num_heads) -> additive bias
    ``(1, h_local, s_q, s_k)`` for self-attention. Replicated table,
    sliced to this rank's head shard inside a TP region."""

    config: T5Config
    bidirectional: bool = True

    @nn.compact
    def __call__(self, q_pos, k_pos):
        cfg = self.config
        table = self.param(
            "rel_attn_bias", nn.initializers.normal(0.02),
            (cfg.relative_attention_num_buckets, cfg.num_heads),
            cfg.param_dtype)
        rel = k_pos[None, :] - q_pos[:, None]              # (s_q, s_k)
        bucket = relative_position_bucket(
            rel.astype(jnp.int32), bidirectional=self.bidirectional,
            num_buckets=cfg.relative_attention_num_buckets,
            max_distance=cfg.relative_attention_max_distance)
        bias = table[bucket]                               # (s_q, s_k, H)
        bias = bias.transpose(2, 0, 1)[None]               # (1, H, s_q, s_k)
        tp = cfg.tensor_parallel_size
        if tp > 1 and _axis_bound(MODEL_AXIS):
            h_local = divide(cfg.num_heads, tp)
            r = lax.axis_index(MODEL_AXIS)
            bias = lax.dynamic_slice_in_dim(bias, r * h_local, h_local,
                                            axis=1)
        return bias


class _T5SelfAttention(nn.Module):
    """Bias-free QKV + out projections, unscaled flash attention with the
    shared relative bias; cache-aware for incremental decoding."""

    config: T5Config
    causal: bool = False

    @nn.compact
    def __call__(self, h, bias, cache=None):
        cfg = self.config
        tp = cfg.tensor_parallel_size
        h_local = divide(cfg.num_heads, tp)
        d = cfg.head_dim
        inner = cfg.num_heads * d
        b, s, _ = h.shape

        qkv = ColumnParallelLinear(
            cfg.d_model, 3 * inner, bias=False, gather_output=False,
            world_size=tp, params_dtype=cfg.param_dtype,
            quantize=cfg.quantize_int8, name="qkv")(h)
        q, k, v = jnp.split(qkv, 3, axis=-1)

        def to_bhsd(t):
            return t.reshape(b, s, h_local, d).transpose(0, 2, 1, 3)

        if cache is not None:
            prefill = is_static_prefill(cache, s)
            cache = update_layer_cache(cache, to_bhsd(k), to_bhsd(v))
            if prefill:
                ctx = flash_attention(to_bhsd(q), to_bhsd(k), to_bhsd(v),
                                      bias=bias, causal=self.causal,
                                      scale=1.0)
            else:
                ctx = cached_attention(to_bhsd(q), cache, bias=bias,
                                       scale=1.0)
        else:
            ctx = flash_attention(to_bhsd(q), to_bhsd(k), to_bhsd(v),
                                  bias=bias, causal=self.causal, scale=1.0)
        ctx = ctx.transpose(0, 2, 1, 3).reshape(b, s, h_local * d)
        out = RowParallelLinear(
            inner, cfg.d_model, bias=False, input_is_parallel=True,
            world_size=tp, params_dtype=cfg.param_dtype,
            quantize=cfg.quantize_int8, name="out")(ctx)
        return (out, cache) if cache is not None else out


class _T5CrossAttention(nn.Module):
    """Decoder-to-encoder attention. At decode time the encoder K/V are
    projected ONCE (on the first call, when the cache view lacks them) and
    reused every step — the cross-attention analog of the KV cache."""

    config: T5Config

    @nn.compact
    def __call__(self, h, enc, cache=None):
        cfg = self.config
        tp = cfg.tensor_parallel_size
        h_local = divide(cfg.num_heads, tp)
        d = cfg.head_dim
        inner = cfg.num_heads * d
        b, s, _ = h.shape
        s_enc = enc.shape[1]

        q = ColumnParallelLinear(
            cfg.d_model, inner, bias=False, gather_output=False,
            world_size=tp, params_dtype=cfg.param_dtype,
            quantize=cfg.quantize_int8, name="q")(h)
        kv_proj = ColumnParallelLinear(
            cfg.d_model, 2 * inner, bias=False, gather_output=False,
            world_size=tp, params_dtype=cfg.param_dtype,
            quantize=cfg.quantize_int8, name="kv")

        def to_bhsd(t, length):
            return t.reshape(b, length, h_local, d).transpose(0, 2, 1, 3)

        if cache is not None and "ck" in cache:
            ck, cv = cache["ck"], cache["cv"]
        else:
            kv = kv_proj(enc)
            k, v = jnp.split(kv, 2, axis=-1)
            ck, cv = to_bhsd(k, s_enc), to_bhsd(v, s_enc)
            if cache is not None:
                cache = dict(cache, ck=ck, cv=cv)
        ctx = flash_attention(to_bhsd(q, s), ck, cv, scale=1.0)
        ctx = ctx.transpose(0, 2, 1, 3).reshape(b, s, h_local * d)
        out = RowParallelLinear(
            inner, cfg.d_model, bias=False, input_is_parallel=True,
            world_size=tp, params_dtype=cfg.param_dtype,
            quantize=cfg.quantize_int8, name="out")(ctx)
        return (out, cache) if cache is not None else out


class _T5FFN(nn.Module):
    config: T5Config

    @nn.compact
    def __call__(self, h):
        cfg = self.config
        tp = cfg.tensor_parallel_size
        if cfg.ff_act == "gated-gelu":
            # v1.1: gate+up in one column-parallel GEMM (the Llama pattern)
            wi = ColumnParallelLinear(
                cfg.d_model, 2 * cfg.d_ff, bias=False, gather_output=False,
                world_size=tp, params_dtype=cfg.param_dtype,
                quantize=cfg.quantize_int8, name="wi")(h)
            gate, up = jnp.split(wi, 2, axis=-1)
            act = jax.nn.gelu(gate, approximate=True) * up
        elif cfg.ff_act == "relu":
            act = jax.nn.relu(ColumnParallelLinear(
                cfg.d_model, cfg.d_ff, bias=False, gather_output=False,
                world_size=tp, params_dtype=cfg.param_dtype,
                quantize=cfg.quantize_int8, name="wi")(h))
        else:
            raise ValueError(f"unknown ff_act {cfg.ff_act!r}")
        return RowParallelLinear(
            cfg.d_ff, cfg.d_model, bias=False, input_is_parallel=True,
            world_size=tp, params_dtype=cfg.param_dtype,
            quantize=cfg.quantize_int8, name="wo")(act)


class T5EncoderBlock(nn.Module):
    config: T5Config

    @nn.compact
    def __call__(self, x, bias):
        cfg = self.config
        dt = resolve_compute_dtype(cfg.dtype)
        h = FusedRMSNorm(cfg.d_model, eps=cfg.rms_eps, name="attn_norm")(x)
        x = x + _T5SelfAttention(cfg, causal=False, name="self_attn")(
            h.astype(dt), bias).astype(x.dtype)
        h = FusedRMSNorm(cfg.d_model, eps=cfg.rms_eps, name="ffn_norm")(x)
        return x + _T5FFN(cfg, name="ffn")(h.astype(dt)).astype(x.dtype)


class T5DecoderBlock(nn.Module):
    config: T5Config

    @nn.compact
    def __call__(self, x, enc, bias, cache=None):
        cfg = self.config
        dt = resolve_compute_dtype(cfg.dtype)
        h = FusedRMSNorm(cfg.d_model, eps=cfg.rms_eps, name="attn_norm")(x)
        sa = _T5SelfAttention(cfg, causal=True, name="self_attn")
        if cache is None:
            attn = sa(h.astype(dt), bias)
        else:
            attn, cache = sa(h.astype(dt), bias, cache=cache)
        x = x + attn.astype(x.dtype)
        h = FusedRMSNorm(cfg.d_model, eps=cfg.rms_eps, name="cross_norm")(x)
        ca = _T5CrossAttention(cfg, name="cross_attn")
        if cache is None:
            cross = ca(h.astype(dt), enc)
        else:
            cross, cache = ca(h.astype(dt), enc, cache=cache)
        x = x + cross.astype(x.dtype)
        h = FusedRMSNorm(cfg.d_model, eps=cfg.rms_eps, name="ffn_norm")(x)
        out = x + _T5FFN(cfg, name="ffn")(h.astype(dt)).astype(x.dtype)
        return out if cache is None else (out, cache)


class T5Model(nn.Module):
    """Encoder-decoder LM. ``__call__(encoder_ids, decoder_ids)`` returns
    vocab-PARALLEL logits over the decoder positions (teacher forcing);
    ``encode``/``decode`` split the two halves for generation
    (models/t5.py:t5_generate). The LM head is the tied embedding scaled
    by d_model^-0.5 (the T5 tying convention)."""

    config: T5Config

    def setup(self):
        cfg = self.config
        self.shared = VocabParallelEmbedding(
            cfg.vocab_size, cfg.d_model, world_size=cfg.tensor_parallel_size,
            params_dtype=cfg.param_dtype, name="shared")
        self.enc_bias = T5RelativeBias(cfg, bidirectional=True,
                                       name="enc_rel_bias")
        self.dec_bias = T5RelativeBias(cfg, bidirectional=False,
                                       name="dec_rel_bias")
        self.enc_blocks = [T5EncoderBlock(cfg, name=f"enc_{i}")
                           for i in range(cfg.num_layers)]
        self.dec_blocks = [T5DecoderBlock(cfg, name=f"dec_{i}")
                           for i in range(cfg.num_layers)]
        self.enc_norm = FusedRMSNorm(cfg.d_model, eps=cfg.rms_eps,
                                     name="enc_final_norm")
        self.dec_norm = FusedRMSNorm(cfg.d_model, eps=cfg.rms_eps,
                                     name="dec_final_norm")
        if not cfg.tie_word_embeddings:
            self.lm_head = ColumnParallelLinear(
                cfg.d_model, cfg.vocab_size, bias=False, gather_output=False,
                world_size=cfg.tensor_parallel_size,
                params_dtype=cfg.param_dtype, name="lm_head")

    def _lm_logits(self, x):
        """T5 head convention: tied embedding scaled by d_model^-0.5
        (v1.0) or an independent unscaled lm_head (v1.1)."""
        cfg = self.config
        if cfg.tie_word_embeddings:
            return self.shared.attend(x * (cfg.d_model ** -0.5))
        return self.lm_head(x)

    def encode(self, encoder_ids):
        cfg = self.config
        dt = resolve_compute_dtype(cfg.dtype)
        s = encoder_ids.shape[1]
        pos = jnp.arange(s, dtype=jnp.int32)
        bias = self.enc_bias(pos, pos).astype(dt)
        x = self.shared(encoder_ids).astype(dt)
        for blk in self.enc_blocks:
            x = blk(x, bias)
        return self.enc_norm(x).astype(dt)

    def decode(self, decoder_ids, enc, cache=None):
        """Teacher-forced (cache=None) or incremental decode against a
        computed encoder representation. Cache layout matches
        models/generation.py, with per-layer ``ck``/``cv`` encoder K/V
        added by the first call."""
        cfg = self.config
        dt = resolve_compute_dtype(cfg.dtype)
        s = decoder_ids.shape[1]
        x = self.shared(decoder_ids).astype(dt)
        if cache is None:
            pos = jnp.arange(s, dtype=jnp.int32)
            bias = self.dec_bias(pos, pos).astype(dt)
            for blk in self.dec_blocks:
                x = blk(x, enc, bias)
        else:
            from apex_tpu.models.generation import (advance_cache,
                                                    check_chunk_bounds,
                                                    is_paged, layer_cache)

            if is_paged(cache):
                raise NotImplementedError(
                    "paged serving decode (apex_tpu/serving) covers the "
                    "decoder-only families (GPT, Llama); T5 needs "
                    "per-slot relative-position bias and paged "
                    "cross-attention")
            t0 = check_chunk_bounds(cache, s, cfg.max_position_embeddings)
            t_max = cache["layers"][0]["k"].shape[2]
            q_pos = t0 + jnp.arange(s, dtype=jnp.int32)
            k_pos = jnp.arange(t_max, dtype=jnp.int32)
            bias = self.dec_bias(q_pos, k_pos).astype(dt)
            if is_static_prefill(layer_cache(cache, 0), s):
                # the flash prefill sees only the chunk's keys, not the
                # whole buffer: slice the bias to the chunk square
                bias_prefill = bias[:, :, :, :s]
            new_layers = []
            for i, blk in enumerate(self.dec_blocks):
                lc = layer_cache(cache, i)
                blk_bias = bias_prefill if is_static_prefill(lc, s) else bias
                x, lc = blk(x, enc, blk_bias, cache=lc)
                new_layers.append(lc)
            x = self.dec_norm(x).astype(dt)
            logits = self._lm_logits(x)
            # ck/cv ride each layer dict (advance_cache keeps extras)
            return logits, advance_cache(cache, new_layers, s)
        x = self.dec_norm(x).astype(dt)
        return self._lm_logits(x)

    def __call__(self, encoder_ids, decoder_ids):
        return self.decode(decoder_ids, self.encode(encoder_ids))


def t5_loss(model: T5Model, variables, encoder_ids, decoder_ids, labels,
            axis_name: str = MODEL_AXIS):
    """Mean token loss over decoder positions (teacher forcing)."""
    logits = model.apply(variables, encoder_ids, decoder_ids)
    return lm_token_loss(logits, labels, axis_name=axis_name)


def _validate_t5_decode(cfg: T5Config, max_new_tokens: int) -> None:
    """Shared decode-cap validation (start token + generated tokens must
    fit the static cache/bias tables)."""
    if max_new_tokens < 1:
        raise ValueError("max_new_tokens must be >= 1")
    if max_new_tokens + 1 > cfg.max_position_embeddings:
        raise ValueError(
            f"max_new_tokens={max_new_tokens} exceeds the decode cap "
            f"max_position_embeddings={cfg.max_position_embeddings}")


def t5_generate(model: T5Model, variables, encoder_ids,
                max_new_tokens: int, *, temperature: float = 0.0,
                top_k=None, top_p=None, rng=None, eos_token_id=None,
                axis_name: str = MODEL_AXIS):
    """Encode once, then autoregressively decode from
    ``decoder_start_token_id``: the encoder-decoder analog of
    ``generation.generate`` (same static cache, flash/dense split, and
    sampling). Returns ``(batch, max_new_tokens)`` decoder tokens (the
    start token is not included)."""
    from apex_tpu.models.generation import (decode_loop, init_cache,
                                            seal_cache, validate_sampling)

    cfg = model.config
    b = encoder_ids.shape[0]
    _validate_t5_decode(cfg, max_new_tokens)
    rng = validate_sampling(temperature, top_k, top_p, rng)

    enc = model.apply(variables, encoder_ids, method=T5Model.encode)
    cache = init_cache(cfg, b, max_new_tokens + 1)
    start = jnp.full((b, 1), cfg.decoder_start_token_id, jnp.int32)
    logits, cache = model.apply(variables, start, enc, cache,
                                method=T5Model.decode)
    cache = seal_cache(cache)

    return decode_loop(
        lambda tok, c: model.apply(variables, tok[:, None], enc, c,
                                   method=T5Model.decode),
        logits, cache, max_new_tokens, temperature=temperature, top_k=top_k,
        top_p=top_p, rng=rng, eos_token_id=eos_token_id, axis_name=axis_name)


def t5_beam_search(model: T5Model, variables, encoder_ids,
                   max_new_tokens: int, *, num_beams: int,
                   eos_token_id=None, length_penalty: float = 1.0,
                   axis_name: str = MODEL_AXIS):
    """Beam-search decode for the encoder-decoder family: encode once,
    replicate the encoder output per beam, run the shared
    ``beam_search_loop`` (generation.py — beams fold into the batch, cache
    reorder is a leading-dim gather incl. the cross ck/cv). Returns
    ``(sequences (b, num_beams, max_new_tokens), scores)``, best first."""
    from apex_tpu.models.generation import (beam_search_loop, init_cache,
                                            repeat_cache, seal_cache)

    cfg = model.config
    b = encoder_ids.shape[0]
    if num_beams < 1:
        raise ValueError("num_beams must be >= 1")
    _validate_t5_decode(cfg, max_new_tokens)

    # encode + start-token prefill ONCE at batch b (incl. the cross-KV
    # projection); fan the cache out to the beam-folded batch afterwards
    enc = model.apply(variables, encoder_ids, method=T5Model.encode)
    cache = init_cache(cfg, b, max_new_tokens + 1)
    start = jnp.full((b, 1), cfg.decoder_start_token_id, jnp.int32)
    logits, cache = model.apply(variables, start, enc, cache,
                                method=T5Model.decode)
    cache = seal_cache(repeat_cache(cache, num_beams))
    logits = jnp.repeat(logits, num_beams, axis=0)
    # steps read cross K/V from the cache; enc_rep only rides the call
    # signature (dead operand under "ck" in cache)
    enc_rep = jnp.repeat(enc, num_beams, axis=0)
    return beam_search_loop(
        lambda tok, c: model.apply(variables, tok[:, None], enc_rep, c,
                                   method=T5Model.decode),
        logits, cache, max_new_tokens, batch=b, num_beams=num_beams,
        eos_token_id=eos_token_id, length_penalty=length_penalty,
        # length_offset stays 0: transformers >= 4.36 normalizes by
        # cur_len + 1 - decoder_prompt_len — the decoder_start token is
        # EXCLUDED (generated tokens only; ADVICE r5)
        axis_name=axis_name)
