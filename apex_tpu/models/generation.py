"""Autoregressive decoding with a static-shape KV cache (beyond reference).

The reference (apex) is a training-utilities library and ships no inference
path; a complete framework needs one. This module is the TPU-first decode
design:

- **Static shapes everywhere**: the cache is allocated once at
  ``(batch, kv_heads_local, max_len, head_dim)`` per layer; each step
  writes its chunk with ``lax.dynamic_update_slice`` and attends over the
  full buffer with an absolute-position mask. No growing arrays, no
  recompilation per step.
- **Prefill rides the flash kernel**: the cache length starts as a STATIC
  Python 0 and stays static under plain-int arithmetic, so the first
  (prompt) chunk is provably past-free at trace time and the blocks route
  it through the same Pallas flash attention as training — O(tile) memory
  instead of materializing ``(b, kv, rep, s, max_len)`` scores. Decode
  steps (traced length inside ``lax.scan``) use the masked dot-product
  over the cache, where the score tensor is a thin ``s=1`` slab.
- **One compiled loop**: the decode loop is a ``lax.scan`` over steps, so
  the whole ``generate`` call is a single XLA program (jittable end to
  end); the per-step cache update aliases in place under XLA.
- **Tensor-parallel native**: caches hold the LOCAL kv-head shard (GQA
  divides kv heads over the ``model`` axis exactly like training), and
  sampling all-gathers only the final-position vocab-parallel logits
  (payload ``[batch, vocab]``) — the replicated PRNG key then makes every
  rank sample the same token.
- **GQA/MQA without expansion**: queries reshape to
  ``(b, kv, rep, s, d)`` and contract against the unexpanded K/V cache —
  the cache stays ``num_kv_heads``-sized in HBM (Llama/Mistral GQA).

Prefill and decode share one model entry point: ``model.apply(variables,
ids, cache=cache)`` returns ``(vocab-parallel logits, updated cache)`` for
any chunk length, so chunked/speculative decoding composes for free. While
the cache length is static (prefill + chunked continuation outside the
scan) out-of-range chunks raise at trace time; once the length is traced
(inside ``generate``'s scan) bounds are enforced by ``generate`` itself —
callers driving ``apply`` directly with a traced length own that check
(``lax.dynamic_slice`` clamps silently).

Context parallelism does not compose with incremental decoding (the cache
is position-contiguous per device); the models raise on that combination.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from apex_tpu.amp.policy import resolve_compute_dtype
from apex_tpu.mesh import MODEL_AXIS
from apex_tpu.ops import quant
from apex_tpu.transformer.tensor_parallel.mappings import (
    axis_is_bound as _axis_bound,
    gather_from_tensor_model_parallel_region,
)
from apex_tpu.transformer.utils import divide


# --- cache structure ---------------------------------------------------------
#
# cache = {"layers": [{"k": (b, kv_local, T, d), "v": ...}] * num_layers,
#          "len":   tokens already written — a Python int while static
#                   (prefill, chunked continuation), an int32 scalar inside
#                   the decode scan}
#
# The per-layer view handed to a decoder block adds the current length so
# the block can place its chunk: {"k", "v", "len"}.


def init_cache(config, batch: int, max_len: int, *, dtype=None):
    """Allocate an all-zeros KV cache for ``batch`` sequences of up to
    ``max_len`` total tokens (prompt + generated). Inside shard_map with
    the ``model`` axis bound, ``config.tensor_parallel_size`` kv-head
    shards divide exactly as in training.

    With ``config.rolling_cache`` (sliding-window models), the buffer is a
    ROLLING ring of ``sliding_window`` slots instead of ``max_len`` —
    O(window) HBM for arbitrarily long decodes (the Mistral serving
    pattern); writes wrap modulo the window and the mask reconstructs each
    slot's absolute position."""
    kv_heads = getattr(config, "num_kv_heads", config.num_heads)
    kv_local = divide(kv_heads, config.tensor_parallel_size)
    d = config.head_dim
    dt = dtype if dtype is not None else resolve_compute_dtype(config.dtype)
    t_buf = max_len
    if getattr(config, "rolling_cache", False):
        if not getattr(config, "sliding_window", None):
            raise ValueError("rolling_cache requires sliding_window")
        # ALWAYS window-sized: a ring shorter than the window would
        # silently drop reachable positions once decoding passes its size
        t_buf = config.sliding_window
    shape = (batch, kv_local, t_buf, d)
    layers = [{"k": jnp.zeros(shape, dt), "v": jnp.zeros(shape, dt)}
              for _ in range(config.num_layers)]
    return {"layers": layers, "len": 0}


def cache_max_len(cache) -> int:
    return cache["layers"][0]["k"].shape[2]


def check_chunk_bounds(cache, s: int, max_position_embeddings: int, *,
                       rolling: bool = False):
    """Model-level guard for a chunk of length ``s``: while the cache
    length is static, out-of-range chunks (past the position table or the
    cache buffer) raise at trace time — the decode-path analog of the
    training forward's explicit position checks. Returns the offset.
    A ``rolling`` buffer wraps, so only the position cap applies."""
    t0 = cache["len"]
    t_max = cache_max_len(cache)
    if isinstance(t0, int):
        if t0 + s > max_position_embeddings:
            raise ValueError(
                f"decode chunk [{t0}, {t0 + s}) exceeds "
                f"max_position_embeddings={max_position_embeddings}")
        if not rolling and t0 + s > t_max:
            raise ValueError(
                f"decode chunk [{t0}, {t0 + s}) exceeds the cache buffer "
                f"(max_len={t_max}); allocate a larger init_cache")
    elif not rolling and s > t_max:
        raise ValueError(f"chunk length {s} exceeds cache max_len={t_max}")
    return t0


def is_paged(cache) -> bool:
    """True for a paged serving cache (``apex_tpu/serving/kv_pool.py``):
    per-layer page pools + per-SLOT block tables and lengths, recognized
    by the ``block_tables`` key. ``cache["len"]`` is then a
    ``(num_slots,)`` vector, not a scalar."""
    return "block_tables" in cache


def layer_cache(cache, i: int):
    """Per-layer view for decoder block ``i`` (adds the shared length —
    and, for a paged cache, the shared block tables)."""
    lc = dict(cache["layers"][i])
    lc["len"] = cache["len"]
    if is_paged(cache):
        lc["block_tables"] = cache["block_tables"]
    return lc


def is_static_prefill(lc, s: int) -> bool:
    """True when this chunk is provably the first tokens in the cache AT
    TRACE TIME — the blocks then attend with the training flash kernel
    (past-free, O(tile) memory) instead of the dense cached path."""
    return isinstance(lc["len"], int) and lc["len"] == 0 and s > 1


def update_layer_cache(lc, k_chunk, v_chunk):
    """Write a ``(b, kv, s, d)`` K/V chunk at offset ``len`` and return the
    updated per-layer view. XLA aliases the update in place inside jit.

    TRACED-length caveat: with a sealed (traced) ``len`` the bounds cannot
    be checked at trace time and ``lax.dynamic_update_slice`` CLAMPS an
    out-of-range start, silently overwriting the newest cache entries —
    callers driving ``model.apply`` inside their own scan own the bound
    (``generate`` enforces it up front; static lengths raise in
    ``check_chunk_bounds``)."""
    t0 = lc["len"]
    start = (0, 0, t0, 0)
    out = dict(lc)  # preserve extra entries (e.g. T5's cross ck/cv)
    out["k"] = lax.dynamic_update_slice(lc["k"],
                                        k_chunk.astype(lc["k"].dtype), start)
    out["v"] = lax.dynamic_update_slice(lc["v"],
                                        v_chunk.astype(lc["v"].dtype), start)
    return out


def _append_quantized_pages(pages, scales, chunk, bt, t, ps, max_pages,
                            qmax):
    """Quantized-pool append with REQUANTIZE-ON-GROW (docs/serving.md
    "Quantized KV pages"): the ``s <= page_size`` chunk spans at most the
    boundary page and its successor, so two sequential rounds each (1)
    take the per-(slot, kv_head) amax of the new tokens landing in that
    page, (2) grow the page's symmetric scale monotonically
    (``new = max(old, amax/qmax)``), (3) rescale the page's EXISTING
    quantized contents onto the grown grid (ratio 1 — the common case —
    is a bit-exact rewrite), and (4) merge the new tokens quantized at
    the new scale. Only pages at or past ``len // page_size`` are ever
    touched, so full pages — the prefix cache's sharing unit and the
    preemption spill set — stay bit-stable forever."""
    slots, kvh, s, d = chunk.shape
    cf = chunk.astype(jnp.float32)
    pos = t[:, None] + jnp.arange(s, dtype=t.dtype)[None, :]  # (slots, s)
    base = t // ps
    sl = jnp.arange(slots)
    for j in (0, 1):
        ent = base + j
        pg = jnp.take_along_axis(
            bt, jnp.clip(ent, 0, max_pages - 1)[:, None], axis=1)[:, 0]
        in_pg = (pos // ps) == ent[:, None]                  # (slots, s)
        has = in_pg.any(axis=1)
        amax = jnp.where(in_pg[:, None, :, None], jnp.abs(cf), 0.0
                         ).max(axis=(2, 3))                  # (slots, kv)
        old = scales[pg]
        new = jnp.where(has[:, None], jnp.maximum(old, amax / qmax), old)
        ratio = jnp.where(new > 0, old / jnp.maximum(new, 1e-30), 0.0)
        tile = pages[pg].astype(jnp.float32) * ratio[:, :, None, None]
        tile_q = quant.kv_cast(tile, pages.dtype, qmax)
        inv = jnp.where(new > 0, 1.0 / jnp.maximum(new, 1e-30), 0.0)
        qtok = quant.kv_cast(cf * inv[:, :, None, None], pages.dtype,
                             qmax)
        # members scatter at their in-page offset; non-members drop at
        # the out-of-range offset ps
        off = jnp.where(in_pg, pos % ps, ps)                 # (slots, s)
        tile_q = tile_q.at[sl[:, None], :, off, :].set(
            qtok.transpose(0, 2, 1, 3), mode="drop")
        # distinct live slots own distinct pages; idle/done rows collide
        # only on the garbage null page 0, which no live slot reads
        pages = pages.at[pg].set(tile_q)
        scales = scales.at[pg].set(new)
    return pages, scales


def update_paged_layer_cache(lc, k_chunk, v_chunk):
    """Write an ``(slots, kv, s, d)`` K/V chunk into the page pool at each
    slot's current length: slot ``b``'s chunk position ``i`` lands in page
    ``block_tables[b, (len_b + i) // page_size]`` at offset
    ``(len_b + i) % page_size``. Distinct slots own distinct pages and a
    slot's ``s`` positions are distinct ``(page, offset)`` pairs (callers
    keep ``s <= page_size``, the paged kernel's own bound), so the scatter
    indices never collide; an idle slot (block table row all null-page)
    writes into the reserved page 0, which no live sequence ever reads.

    A QUANTIZED pool (``k_scales`` in the layer view) quantizes on write:
    the chunk's pages requantize-on-grow through
    :func:`_append_quantized_pages`, and the per-page scales ride the
    layer view back to the model's ``paged_attention`` call."""
    ps = lc["k_pages"].shape[2]
    max_pages = lc["block_tables"].shape[1]
    s = k_chunk.shape[2]
    t = lc["len"]                                            # (slots,)
    out = dict(lc)
    if "k_scales" in lc:
        qmax = quant.kv_qmax(lc["k_pages"].dtype)
        out["k_pages"], out["k_scales"] = _append_quantized_pages(
            lc["k_pages"], lc["k_scales"], k_chunk, lc["block_tables"],
            t, ps, max_pages, qmax)
        out["v_pages"], out["v_scales"] = _append_quantized_pages(
            lc["v_pages"], lc["v_scales"], v_chunk, lc["block_tables"],
            t, ps, max_pages, qmax)
        return out
    pos = t[:, None] + jnp.arange(s, dtype=t.dtype)[None, :]  # (slots, s)
    page = jnp.take_along_axis(
        lc["block_tables"], jnp.clip(pos // ps, 0, max_pages - 1), axis=1)
    off = pos % ps
    # advanced-index dims lead: [page, :, off, :] scatters (slots, s)
    # index pairs over (kv, d) tiles — values arrive position-major
    out["k_pages"] = lc["k_pages"].at[page, :, off, :].set(
        k_chunk.transpose(0, 2, 1, 3).astype(lc["k_pages"].dtype))
    out["v_pages"] = lc["v_pages"].at[page, :, off, :].set(
        v_chunk.transpose(0, 2, 1, 3).astype(lc["v_pages"].dtype))
    return out


def update_layer_cache_rolling(lc, k_chunk, v_chunk):
    """Ring-buffer write: the chunk's positions land at ``pos % R``. Only
    the LAST ``min(s, R)`` chunk positions are kept (earlier ones would
    collide with slots later writes need, and a window model never reads
    past its band anyway). Duplicate-free scatter indices by construction."""
    t0 = lc["len"]
    r = lc["k"].shape[2]
    s = k_chunk.shape[2]
    keep = min(s, r)
    k_tail = k_chunk[:, :, s - keep:, :]
    v_tail = v_chunk[:, :, s - keep:, :]
    idx = (t0 + (s - keep) + jnp.arange(keep, dtype=jnp.int32)) % r
    out = dict(lc)
    out["k"] = lc["k"].at[:, :, idx, :].set(k_tail.astype(lc["k"].dtype))
    out["v"] = lc["v"].at[:, :, idx, :].set(v_tail.astype(lc["v"].dtype))
    return out


def _masked_attention_core(q, k, v, mask, *, scale, bias=None):
    """Shared GQA dot-product core for the cached paths: fp32 scores +
    accumulation, queries grouped against the unexpanded kv-head buffer,
    ``mask`` broadcastable to ``(b, kv, rep, s, T)``."""
    b, h, s, d = q.shape
    kv = k.shape[1]
    rep = divide(h, kv)
    t_max = k.shape[2]

    qf = q.reshape(b, kv, rep, s, d).astype(jnp.float32)
    scores = jnp.einsum("bkrsd,bktd->bkrst", qf, k.astype(jnp.float32),
                        preferred_element_type=jnp.float32)
    scores = scores * (jnp.float32(scale) if scale is not None
                       else 1.0 / jnp.sqrt(jnp.float32(d)))
    if bias is not None:
        bb = jnp.broadcast_to(bias.astype(jnp.float32), (b, h, s, t_max))
        scores = scores + bb.reshape(b, kv, rep, s, t_max)
    scores = jnp.where(mask, scores, -jnp.inf)
    p = jax.nn.softmax(scores, axis=-1)
    ctx = jnp.einsum("bkrst,bktd->bkrsd", p, v.astype(jnp.float32),
                     preferred_element_type=jnp.float32)
    return ctx.reshape(b, h, s, d).astype(q.dtype)


def cached_attention_rolling(q, lc, *, window: int,
                             scale: Optional[float] = None):
    """Single-step (``s=1``) attention over the rolling ring: slot ``j``'s
    absolute position is reconstructed from the write pointer
    (``last - ((last - j) mod R)``), masked to the causal window band and
    to written slots. Multi-token chunks are unsupported on the ring (a
    later in-chunk write would overwrite a slot an earlier query needs)."""
    k, v, t0 = lc["k"], lc["v"], lc["len"]
    if q.shape[2] != 1:
        raise NotImplementedError(
            "rolling cache supports single-token decode steps only "
            "(prefill rides the flash kernel; chunked continuation / "
            "speculative verification need the full buffer)")
    r = k.shape[2]
    last = t0                                  # this step's absolute position
    slots = jnp.arange(r, dtype=jnp.int32)
    p_j = last - ((last - slots) % r)          # slot -> absolute position
    mask = jnp.logical_and(p_j >= 0, p_j > last - window)
    return _masked_attention_core(q, k, v, mask[None, None, None, None],
                                  scale=scale)


def advance_cache(cache, new_layers, s: int):
    """Model-level reassembly after all blocks ran a chunk of length s.
    Plain-int arithmetic keeps a static length static across chunks; the
    per-layer entries keep everything but the shared keys (length, paged
    block tables) — including model-specific extras like T5's cross
    ``ck``/``cv``. Top-level extras (a paged cache's block tables and free
    list) pass through untouched; a paged ``len`` is a per-slot vector and
    advances elementwise."""
    out = dict(cache)
    out["layers"] = [{k: v for k, v in lc.items()
                      if k not in ("len", "block_tables")}
                     for lc in new_layers]
    out["len"] = cache["len"] + s
    return out


def seal_cache(cache):
    """Convert a static length to a traced int32 scalar so the cache can be
    a ``lax.scan`` carry (the decode loop's representation)."""
    return dict(cache, len=jnp.asarray(cache["len"], jnp.int32))


def cached_attention(q, lc, *, window: Optional[int] = None, bias=None,
                     scale: Optional[float] = None):
    """Masked dot-product attention of a ``(b, h, s, d)`` query chunk at
    absolute positions ``[len, len+s)`` against the full cache buffer.

    The causal mask is over ABSOLUTE positions (key j visible to query at
    global position p iff ``p - window < j <= p``), which simultaneously
    hides the not-yet-written tail of the static buffer. GQA contracts the
    grouped queries against the unexpanded kv-head cache. fp32 scores and
    accumulation (same numerics contract as the flash kernel). ``bias``
    (broadcastable to ``(b, h, s, t_max)``, e.g. T5 relative-position
    bias) adds to the scaled scores before masking — the cached analog of
    the flash kernel's additive slot."""
    k, v, t0 = lc["k"], lc["v"], lc["len"]
    s = q.shape[2]
    t_max = k.shape[2]
    pos_q = t0 + jnp.arange(s, dtype=jnp.int32)[:, None]      # (s, 1)
    pos_k = jnp.arange(t_max, dtype=jnp.int32)[None, :]       # (1, T)
    mask = pos_k <= pos_q
    if window is not None:
        mask = jnp.logical_and(mask, pos_k > pos_q - window)
    return _masked_attention_core(q, k, v, mask[None, None, None],
                                  scale=scale, bias=bias)


# --- sampling + the generate loop -------------------------------------------


def _greedy_token(logits, axis_name):
    """fp32 argmax over (possibly vocab-parallel) logits' last axis —
    the shared greedy primitive for sampling and speculative verify."""
    if _axis_bound(axis_name):
        logits = gather_from_tensor_model_parallel_region(logits, axis_name)
    return jnp.argmax(logits.astype(jnp.float32), axis=-1).astype(jnp.int32)


def _sample_token(last_logits, step_key, *, temperature, top_k, top_p,
                  axis_name):
    """One token per batch row from final-position (possibly vocab-parallel)
    logits. Greedy at temperature 0; otherwise top-k/top-p/categorical.
    Inside a TP region the gather makes logits (and the replicated key makes
    the draw) identical on every rank."""
    if not temperature:
        return _greedy_token(last_logits, axis_name)
    if _axis_bound(axis_name):
        last_logits = gather_from_tensor_model_parallel_region(
            last_logits, axis_name)
    logits = last_logits.astype(jnp.float32) / temperature
    if top_k is not None:
        kth = lax.top_k(logits, top_k)[0][..., -1:]
        logits = jnp.where(logits < kth, -jnp.inf, logits)
    if top_p is not None:
        # nucleus: keep the smallest prefix of the sorted distribution with
        # cumulative mass > top_p (the first token always survives: the
        # EXCLUSIVE cumsum below is 0.0 < top_p for it)
        sorted_logits = jnp.sort(logits, axis=-1)[..., ::-1]
        probs = jax.nn.softmax(sorted_logits, axis=-1)
        mass_before = jnp.cumsum(probs, axis=-1) - probs
        cutoff_idx = jnp.sum((mass_before < top_p).astype(jnp.int32),
                             axis=-1, keepdims=True) - 1
        cutoff = jnp.take_along_axis(sorted_logits, cutoff_idx, axis=-1)
        logits = jnp.where(logits < cutoff, -jnp.inf, logits)
    return jax.random.categorical(step_key, logits, axis=-1).astype(jnp.int32)


def validate_sampling(temperature, top_k, top_p, rng):
    """Shared sampling-knob validation for the decode loops; returns the
    effective rng."""
    if temperature and rng is None:
        raise ValueError("sampling (temperature > 0) needs an explicit rng")
    if not temperature and (top_k is not None or top_p is not None
                            or rng is not None):
        # the mirror-image misuse: sampling knobs with greedy decoding
        # would be silently ignored
        raise ValueError("top_k/top_p/rng require temperature > 0 (greedy "
                         "decoding at temperature=0 ignores them)")
    if top_k is not None and top_k < 1:
        raise ValueError(f"top_k must be >= 1, got {top_k}")
    if top_p is not None and not 0.0 < top_p <= 1.0:
        # top_p <= 0 would otherwise hit the exclusive-cumsum edge (no row
        # below the threshold -> index -1 -> smallest logit as cutoff) and
        # silently sample the FULL distribution
        raise ValueError(f"top_p must be in (0, 1], got {top_p}")
    return rng if rng is not None else jax.random.PRNGKey(0)


def decode_loop(step_apply, prefill_logits, cache, max_new_tokens: int, *,
                temperature, top_k, top_p, rng, eos_token_id, axis_name):
    """The shared sampled-decode scan (decoder-only AND encoder-decoder
    models): ``step_apply(tok_(b,), cache) -> (logits_(b,1,V), cache)``.
    Samples the first token from ``prefill_logits[:, -1]``, then scans
    single-token steps; EOS rows keep emitting EOS. Returns the
    ``(b, max_new_tokens)`` generated tokens."""
    b = prefill_logits.shape[0]

    def sample(last, i):
        return _sample_token(last, jax.random.fold_in(rng, i),
                             temperature=temperature, top_k=top_k,
                             top_p=top_p, axis_name=axis_name)

    tok0 = sample(prefill_logits[:, -1], 0)
    done0 = (tok0 == eos_token_id) if eos_token_id is not None \
        else jnp.zeros((b,), bool)

    def step(carry, i):
        cache, tok, done = carry
        step_logits, cache = step_apply(tok, cache)
        nxt = sample(step_logits[:, 0], i)
        if eos_token_id is not None:
            nxt = jnp.where(done, jnp.int32(eos_token_id), nxt)
            done = jnp.logical_or(done, nxt == eos_token_id)
        return (cache, nxt, done), nxt

    if max_new_tokens > 1:
        # without an eos the `done` carry is vestigial (never read) —
        # kept so the scan signature is identical across eos modes
        # tpu-lint: disable=ir-dead-scan-carry -- one (b,) bool per step
        _, rest = lax.scan(step, (cache, tok0, done0),
                           jnp.arange(1, max_new_tokens))
        return jnp.concatenate([tok0[:, None], rest.T], axis=1)
    return tok0[:, None]


def generate(model, variables, prompt_ids, max_new_tokens: int, *,
             max_len: Optional[int] = None, temperature: float = 0.0,
             top_k: Optional[int] = None, top_p: Optional[float] = None,
             rng=None, eos_token_id: Optional[int] = None,
             axis_name: str = MODEL_AXIS, paged: bool = False,
             num_slots: Optional[int] = None, page_size: int = 16,
             prefix_cache: bool = False, kv_dtype=None):
    """Prefill the prompt (flash-kernel path), then scan ``max_new_tokens``
    single-token decode steps. Returns ``(batch, prompt_len +
    max_new_tokens)`` token ids (prompt included). After ``eos_token_id``
    a row keeps emitting EOS.

    Jittable end to end (``max_new_tokens`` static). Works plain, under
    ``jit`` with a dp-sharded batch, or inside ``shard_map`` with the
    ``model`` axis bound (vocab-/head-sharded decode).

    ``paged=True`` routes the batch through the continuous-batching
    serving engine (``apex_tpu/serving``): each row becomes a queued
    request over ``num_slots`` decode slots (default: the batch size)
    backed by a paged KV pool — same greedy output, but EOS rows retire
    and free their slot/pages instead of padding to ``max_new_tokens``.
    Host-driven (not jittable as one program); greedy path is
    token-identical to the lock-step scan. ``prefix_cache=True`` (paged
    only) additionally shares cached K/V pages across requests with a
    common prompt prefix — same outputs, prefill skipped for the shared
    pages (``apex_tpu/serving/prefix_cache.py``). ``kv_dtype`` (paged
    only) stores the pool's K/V pages quantized (``"int8"`` or
    ``"fp8"``/``"e4m3"``) with per-(page, kv_head) scales, dequantized
    inside the paged kernel — greedy output then matches the fp pool to
    tolerance, not bit-exactly (docs/serving.md "Quantized KV pages")."""
    if prefix_cache and not paged:
        raise ValueError("prefix_cache requires paged=True (sharing lives "
                         "in the page pool)")
    if kv_dtype is not None and not paged:
        raise ValueError("kv-dtype-unsupported: kv_dtype requires "
                         "paged=True (quantized K/V lives in the page "
                         "pool; the lock-step cache is full-precision)")
    if paged:
        from apex_tpu.serving import generate_paged

        # same bounds contract as the lock-step path (max_len has no
        # paged meaning beyond validation — the pool allocates by need)
        validate_decode_bounds(prompt_ids.shape[1], max_new_tokens,
                               model.config.max_position_embeddings,
                               max_len)
        return generate_paged(
            model, variables, prompt_ids, max_new_tokens,
            temperature=temperature, top_k=top_k, top_p=top_p, rng=rng,
            eos_token_id=eos_token_id, axis_name=axis_name,
            num_slots=num_slots, page_size=page_size,
            prefix_cache=prefix_cache, kv_dtype=kv_dtype)
    cfg = model.config
    b, s0 = prompt_ids.shape
    t_max = validate_decode_bounds(s0, max_new_tokens,
                                   cfg.max_position_embeddings, max_len)
    rng = validate_sampling(temperature, top_k, top_p, rng)

    cache = init_cache(cfg, b, t_max)
    logits, cache = model.apply(variables, prompt_ids, cache=cache)
    cache = seal_cache(cache)  # static len -> scan-carry representation

    gen = decode_loop(
        lambda tok, c: model.apply(variables, tok[:, None], cache=c),
        logits, cache, max_new_tokens, temperature=temperature, top_k=top_k,
        top_p=top_p, rng=rng, eos_token_id=eos_token_id, axis_name=axis_name)
    return jnp.concatenate([prompt_ids.astype(jnp.int32), gen], axis=1)


# --- beam search -------------------------------------------------------------


def validate_decode_bounds(s0: int, max_new_tokens: int,
                           max_position_embeddings: int,
                           max_len=None) -> int:
    """Shared prompt/cap/buffer validation for the decode entry points;
    returns the effective cache length."""
    # decode bounds are Python ints by contract; under
    # jit(partial(generate, ...)) they concretize at trace time (static),
    # tpu-lint: disable=host-sync-in-jit -- never against a device value
    total = s0 + int(max_new_tokens)
    if max_new_tokens < 1:
        raise ValueError("max_new_tokens must be >= 1")
    if total > max_position_embeddings:
        raise ValueError(
            f"prompt ({s0}) + max_new_tokens ({max_new_tokens}) exceeds "
            f"max_position_embeddings={max_position_embeddings}")
    t_max = total if max_len is None else int(max_len)  # tpu-lint: disable=host-sync-in-jit -- static bound, see above
    if t_max < total:
        raise ValueError(f"max_len={t_max} < prompt + max_new_tokens={total}")
    return t_max


def repeat_cache(cache, times: int):
    """Replicate every per-sequence cache row ``times`` along the leading
    dim (row layout ``[b0 x times, b1 x times, ...]``) — beam search
    prefills ONCE at batch b and fans the cache out to b*W afterwards
    instead of running W identical prompt forwards."""
    def rep(t):
        return jnp.repeat(t, times, axis=0) if hasattr(t, "ndim") \
            and t.ndim >= 1 else t

    return {"layers": [jax.tree.map(rep, lc) for lc in cache["layers"]],
            "len": cache["len"]}


def _gather_beam_cache(cache, parent, batch: int, num_beams: int):
    """Reorder every (batch*num_beams)-leading-dim cache buffer by the
    chosen parents — the beam-search analog of rollback: surviving beams
    inherit their parent's K/V (and any extras like T5's cross ck/cv)."""
    flat = (jnp.arange(batch)[:, None] * num_beams + parent).reshape(-1)
    bw = batch * num_beams

    def reorder(t):
        return t[flat] if (hasattr(t, "ndim") and t.ndim >= 1
                           and t.shape[0] == bw) else t

    return {"layers": [jax.tree.map(reorder, lc) for lc in cache["layers"]],
            "len": cache["len"]}


def _gathered_log_softmax(logits, axis_name):
    if _axis_bound(axis_name):
        logits = gather_from_tensor_model_parallel_region(logits, axis_name)
    return jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)


def beam_search_loop(step_apply, prefill_logits, cache, max_new_tokens: int,
                     *, batch: int, num_beams: int, eos_token_id=None,
                     length_penalty: float = 1.0, length_offset: int = 0,
                     axis_name: str = MODEL_AXIS):
    """Static-shape beam search over a ``(batch*num_beams)``-row cache.

    The beams FOLD INTO THE BATCH dimension, so every step is one batched
    forward (MXU-friendly) and beam reordering is a gather over the cache's
    leading dim (``_gather_beam_cache``). Scan-collected (token, parent)
    backpointers are unwound after the loop — no growing arrays anywhere.
    Finished beams extend only with EOS at zero added score. Final ranking
    divides by ``(length_offset + gen_length)^length_penalty`` where
    ``gen_length`` counts generated tokens up to and including the first
    EOS. ``length_offset`` DEFAULTS TO 0 — the normalizer is the generated
    length only, matching transformers >= 4.36 (``BeamSearchScorer``
    divides by ``cur_len + 1 - decoder_prompt_len``, i.e. prompt and
    decoder-start excluded; ADVICE r5 — the r4 full-hypothesis offset was
    pre-4.36 legacy semantics). Penalty 0 = pure sum-logprob; the offset
    knob remains for callers that want the legacy normalizer.
    Returns ``(sequences (batch, num_beams, max_new_tokens),
    scores (batch, num_beams))``, best beam first.

    ``step_apply(tokens_(batch*num_beams,), cache) -> (logits_(bw,1,V),
    cache)`` — the same contract as ``decode_loop``; ``prefill_logits``
    are the prompt logits with the prompt REPLICATED per beam (row layout
    ``[b0 x W, b1 x W, ...]``)."""
    b, w = batch, num_beams
    neg = jnp.float32(-1e30)   # -inf breaks top_k ties; large-negative safe

    logp0 = _gathered_log_softmax(prefill_logits[:, -1], axis_name)
    vocab = logp0.shape[-1]
    logp0 = logp0.reshape(b, w, vocab)
    # all beams start identical: only beam 0 may seed, else W duplicates
    seed_mask = jnp.where(jnp.arange(w)[None, :, None] == 0, 0.0, neg)
    scores, idx = lax.top_k((logp0 + seed_mask).reshape(b, w * vocab), w)
    tok = (idx % vocab).astype(jnp.int32)                    # (b, w)
    # no cache gather here: at the first expansion every beam's rows are
    # identical prefill replicas, so any reorder is a value-level no-op
    done = (tok == eos_token_id) if eos_token_id is not None \
        else jnp.zeros((b, w), bool)

    def step(carry, _):
        cache, scores, tok, done = carry
        logits, cache = step_apply(tok.reshape(b * w), cache)
        logp = _gathered_log_softmax(logits[:, 0], axis_name)
        logp = logp.reshape(b, w, vocab)
        if eos_token_id is not None:
            # finished beams: EOS-extension only, at no cost — the beam
            # persists in the pool with a frozen score
            eos_only = jnp.full((vocab,), neg).at[eos_token_id].set(0.0)
            logp = jnp.where(done[..., None], eos_only[None, None], logp)
        cand = (scores[..., None] + logp).reshape(b, w * vocab)
        scores, idx = lax.top_k(cand, w)
        tok = (idx % vocab).astype(jnp.int32)
        parent = idx // vocab
        done = jnp.take_along_axis(done, parent, axis=1)
        if eos_token_id is not None:
            done = jnp.logical_or(done, tok == eos_token_id)
        cache = _gather_beam_cache(cache, parent, b, w)
        return (cache, scores, tok, done), (tok, parent)

    if max_new_tokens > 1:
        (_, scores, _, _), (toks, parents) = lax.scan(
            step, (cache, scores, tok, done), None,
            length=max_new_tokens - 1)
    else:
        toks = jnp.zeros((0, b, w), jnp.int32)
        parents = jnp.zeros((0, b, w), jnp.int32)

    # unwind backpointers (python loop over the STATIC step count)
    seq = [None] * max_new_tokens
    beam_idx = jnp.broadcast_to(jnp.arange(w)[None], (b, w))
    for t in range(max_new_tokens - 1, 0, -1):
        seq[t] = jnp.take_along_axis(toks[t - 1], beam_idx, axis=1)
        beam_idx = jnp.take_along_axis(parents[t - 1], beam_idx, axis=1)
    seq[0] = jnp.take_along_axis(tok, beam_idx, axis=1)
    seqs = jnp.stack(seq, axis=-1)                           # (b, w, T)

    if eos_token_id is not None and length_penalty:
        is_eos = seqs == eos_token_id
        # length incl. the first EOS; max_new_tokens when none
        first_eos = jnp.argmax(is_eos, axis=-1) + 1
        lengths = jnp.where(is_eos.any(axis=-1), first_eos, max_new_tokens)
    else:
        lengths = jnp.full((b, w), max_new_tokens)
    lengths = lengths + length_offset  # 0 by default: generated-only (HF)
    final = scores / (lengths.astype(jnp.float32) ** jnp.float32(
        length_penalty))
    order = jnp.argsort(-final, axis=1)
    seqs = jnp.take_along_axis(seqs, order[..., None], axis=1)
    return seqs, jnp.take_along_axis(final, order, axis=1)


def generate_beam(model, variables, prompt_ids, max_new_tokens: int, *,
                  num_beams: int, eos_token_id=None,
                  length_penalty: float = 1.0, max_len=None,
                  axis_name: str = MODEL_AXIS):
    """Beam-search decoding for the decoder-only families: replicate the
    prompt per beam, prefill once, run ``beam_search_loop``. Returns
    ``(sequences (b, num_beams, prompt+max_new), scores (b, num_beams))``,
    best beam first (prompt included in the sequences)."""
    cfg = model.config
    b, s0 = prompt_ids.shape
    if num_beams < 1:
        raise ValueError("num_beams must be >= 1")
    t_max = validate_decode_bounds(s0, max_new_tokens,
                                   cfg.max_position_embeddings, max_len)

    # prefill ONCE at batch b; the beams only diverge after the first
    # expansion, so the cache/logits fan out by replication
    cache = init_cache(cfg, b, t_max)
    logits, cache = model.apply(variables, prompt_ids, cache=cache)
    cache = seal_cache(repeat_cache(cache, num_beams))
    logits = jnp.repeat(logits[:, -1:], num_beams, axis=0)   # (b*w, 1, V)
    seqs, scores = beam_search_loop(
        lambda tok, c: model.apply(variables, tok[:, None], cache=c),
        logits, cache, max_new_tokens, batch=b, num_beams=num_beams,
        eos_token_id=eos_token_id, length_penalty=length_penalty,
        axis_name=axis_name)
    prompt_rep = jnp.broadcast_to(prompt_ids[:, None].astype(jnp.int32),
                                  (b, num_beams, s0))
    return jnp.concatenate([prompt_rep, seqs], axis=-1), scores


# --- speculative decoding ----------------------------------------------------


def rollback_cache(cache, new_len):
    """Rewind a cache to ``new_len`` tokens. O(1): entries past the length
    are already invisible to ``cached_attention``'s absolute-position mask
    and will be overwritten by the next chunk write — rejection rollback is
    just the scalar assignment. (The static-buffer design's payoff.)"""
    return dict(cache, len=new_len)


# module-level jits so the compiled draft/verify programs are shared across
# speculative_generate calls (a per-call closure would re-trace every
# request and bake the weights in as constants)
@functools.partial(jax.jit, static_argnames=("model", "k", "axis_name"))
def _spec_draft_propose(model, variables, dc, first_tok, *, k, axis_name):
    """k draft steps from first_tok: returns (cache at +k tokens, proposals
    d_1..d_{k-1}); the k-th step only advances the draft cache so a
    fully-accepted round leaves it consistent."""
    def one(carry, _):
        dc, tok = carry
        lg, dc = model.apply(variables, tok[:, None], cache=dc)
        return (dc, _greedy_token(lg[:, 0], axis_name)), tok
    (dc, _), toks = lax.scan(one, (dc, first_tok), None, length=k)
    return dc, toks[1:].T                          # (b, k-1) proposals


@functools.partial(jax.jit, static_argnames=("model", "axis_name"))
def _spec_verify(model, variables, tc, chunk, *, axis_name):
    """Target forward on the (b, k) chunk [x_t, d_1..d_{k-1}]: argmax
    predictions for positions t+1..t+k."""
    lg, tc = model.apply(variables, chunk, cache=tc)
    return tc, _greedy_token(lg, axis_name)        # (b, k) argmax tokens


def speculative_generate(model, variables, draft_model, draft_variables,
                         prompt_ids, max_new_tokens: int, *, k: int = 4,
                         axis_name: str = MODEL_AXIS):
    """Greedy speculative decoding: a cheap DRAFT model proposes ``k - 1``
    tokens per round; the target verifies them in ONE ``k``-token chunk
    (an MXU-friendly matmul instead of ``k`` sequential s=1 steps) and
    accepts the longest prefix matching its own argmax. Rejected positions
    roll both caches back (``rollback_cache``) — output is EXACTLY the
    target's greedy decode, for any draft model; the draft only changes
    how many target steps are saved. (Exactness assumes the s=k verify
    forward and the s=1 decode forward agree numerically — guaranteed in
    fp32; under bf16 XLA may tile the two shapes differently, so a
    near-tied argmax can flip and the output is then "target greedy under
    chunked evaluation" rather than bitwise-equal to ``generate``.)

    Batched rows accept the minimum match count across the batch (the
    per-round bonus token — the target's own argmax after the accepted
    prefix — keeps every round's progress >= 1 token/row). Host loop over
    rounds (the accept count is data-dependent); the per-round programs
    are shape-stable, so each jits once. Greedy only; EOS rows are not
    early-stopped (slice the output yourself)."""
    cfg = model.config
    b, s0 = prompt_ids.shape
    total = s0 + int(max_new_tokens)
    if k < 2:
        raise ValueError("k must be >= 2 (k-1 draft proposals per round)")
    if max_new_tokens < 1:
        raise ValueError("max_new_tokens must be >= 1")
    for c in (cfg, draft_model.config):
        # + k: the last round's verification chunk may SPAN positions past
        # the final token before rollback discards them — a chunk crossing
        # the position table's end would make dynamic_slice clamp the
        # whole chunk's positions (corrupting kept tokens too)
        if total + k > c.max_position_embeddings:
            raise ValueError(
                f"prompt ({s0}) + max_new_tokens ({max_new_tokens}) + "
                f"k ({k}) speculative slack exceeds "
                f"max_position_embeddings={c.max_position_embeddings}")

    # + k slack: a round's verification chunk may write up to k tokens past
    # the final accepted position before the rollback discards them
    t_cache = init_cache(cfg, b, total + k)
    d_cache = init_cache(draft_model.config, b, total + k)
    logits, t_cache = model.apply(variables, prompt_ids, cache=t_cache)
    _, d_cache = draft_model.apply(draft_variables, prompt_ids, cache=d_cache)
    t_cache, d_cache = seal_cache(t_cache), seal_cache(d_cache)

    produced = []
    n_out = 0
    next_tok = _greedy_token(logits[:, -1], axis_name)  # guaranteed correct
    while n_out < max_new_tokens:
        x_t = next_tok
        d_cache, props = _spec_draft_propose(
            draft_model, draft_variables, d_cache, x_t, k=k,
            axis_name=axis_name)
        chunk = jnp.concatenate([x_t[:, None], props], axis=1)
        t_cache, preds = _spec_verify(model, variables, t_cache, chunk,
                                      axis_name=axis_name)
        # leading matches of proposals vs target argmax, min over rows
        # (host sync: the accept count steers the Python loop)
        match = (props == preds[:, :-1]).astype(jnp.int32)   # (b, k-1)
        m = int(jnp.min(jnp.sum(jnp.cumprod(match, axis=1), axis=1)))
        produced.append(jnp.concatenate([x_t[:, None], props[:, :m]], axis=1))
        n_out += m + 1
        new_len = t_cache["len"] - (k - (m + 1))   # back to t + 1 + m tokens
        t_cache = rollback_cache(t_cache, new_len)
        d_cache = rollback_cache(d_cache, new_len)
        # the target's own argmax after the accepted prefix is both the
        # round's bonus guarantee and the next round's first token
        next_tok = preds[:, m]
    gen = jnp.concatenate(produced, axis=1)[:, :max_new_tokens]
    return jnp.concatenate([prompt_ids.astype(jnp.int32), gen], axis=1)
