"""HuggingFace → apex_tpu checkpoint conversion (Llama/Mistral, GPT-2,
BERT, T5).

Beyond-reference interop: load a ``transformers`` Llama/Mistral checkpoint
into :class:`apex_tpu.models.llama.LlamaModel`. Pure tensor relayout — the
numerics are asserted identical (tests/test_hf_convert.py compares logits
against ``LlamaForCausalLM`` bit-for-float): both sides use NeoX-style
rotate-half RoPE, fp32 RMSNorm accumulation, and 1/sqrt(d) attention
scaling, so a converted model reproduces the torch forward to float32
tolerance.

Layout notes (HF name -> ours):
- ``self_attn.{k,v}_proj.weight``  -> ``kv_proj/weight`` rows ``[K | V]``
  (our fused projection's per-rank layout)
- ``mlp.{gate,up}_proj.weight``    -> ``gate_up_proj/weight`` ``[gate | up]``
- everything else maps 1:1 (torch linear weights are (out, in), the same
  Megatron layout our TP linears use).
"""

from __future__ import annotations

from typing import Any, Dict

import jax.numpy as jnp
import numpy as np

from apex_tpu.models.llama import LlamaConfig


def _fetch(state_dict, consumed, name, transpose=False):
    """state_dict tensor -> fp32 jnp array (torch tensors detached; the
    consumed-set powers the leftover check in both converters)."""
    consumed.add(name)
    x = state_dict[name]
    if hasattr(x, "detach"):
        # .float() first: numpy cannot represent torch bf16 directly
        x = x.detach().cpu().float().numpy()
    x = np.asarray(x)
    if transpose:
        x = x.T
    return jnp.asarray(x, jnp.float32)


def llama_config_from_hf(hf_config) -> LlamaConfig:
    """Map a ``transformers.LlamaConfig``-like object to ours (fp32 —
    checkpoint conversion is a precision-sensitive context). Raises on
    config features our model does not express (rope scaling, biases,
    non-derived head_dim) instead of silently converting to wrong
    numerics."""
    if getattr(hf_config, "rope_scaling", None):
        raise NotImplementedError(
            "rope_scaling (Llama-3.x scaled RoPE) is not supported by "
            "apex_tpu's _rope_cos_sin — converting would silently change "
            "the numerics")
    for bias_flag in ("attention_bias", "mlp_bias"):
        if getattr(hf_config, bias_flag, False):
            raise NotImplementedError(
                f"{bias_flag}=True checkpoints carry bias tensors our "
                "bias-free Llama blocks cannot hold")
    act = getattr(hf_config, "hidden_act", "silu")
    if act not in ("silu", "swish"):
        raise NotImplementedError(
            f"hidden_act={act!r}: LlamaDecoderBlock hardcodes SwiGLU "
            "(silu) — converting would silently change the numerics")
    derived = hf_config.hidden_size // hf_config.num_attention_heads
    explicit = getattr(hf_config, "head_dim", None)
    if explicit is not None and explicit != derived:
        raise NotImplementedError(
            f"head_dim={explicit} != hidden_size/num_heads={derived}; "
            "LlamaConfig derives head_dim and has no override")
    return LlamaConfig(
        vocab_size=hf_config.vocab_size,
        hidden_size=hf_config.hidden_size,
        intermediate_size=hf_config.intermediate_size,
        num_layers=hf_config.num_hidden_layers,
        num_heads=hf_config.num_attention_heads,
        num_kv_heads=getattr(hf_config, "num_key_value_heads",
                             hf_config.num_attention_heads),
        max_position_embeddings=hf_config.max_position_embeddings,
        rope_theta=getattr(hf_config, "rope_theta", 10000.0),
        rms_eps=hf_config.rms_norm_eps,
        dtype=jnp.float32,
        tie_word_embeddings=bool(
            getattr(hf_config, "tie_word_embeddings", False)),
        sliding_window=getattr(hf_config, "sliding_window", None),
    )


def llama_params_from_hf(state_dict: Dict[str, Any],
                         cfg: LlamaConfig) -> dict:
    """Convert a ``LlamaForCausalLM.state_dict()`` (torch tensors or numpy
    arrays) into the ``LlamaModel`` param tree (tp=1 layout — shard with
    the TP slicers afterwards if needed)."""
    if cfg.tensor_parallel_size != 1:
        raise NotImplementedError(
            "llama_params_from_hf emits the tp=1 layout; convert at tp=1 "
            "and slice per rank (fused projections need per-shard "
            "[K_r|V_r]/[gate_r|up_r] interleaving, not a global concat)")
    consumed = set()

    def t(name):
        return _fetch(state_dict, consumed, name)

    params = {
        "embed_tokens": {"weight": t("model.embed_tokens.weight")},
        "final_norm": {"weight": t("model.norm.weight")},
    }
    if not cfg.tie_word_embeddings:
        params["lm_head"] = {"weight": t("lm_head.weight")}
    for i in range(cfg.num_layers):
        p = f"model.layers.{i}."
        params[f"layer_{i}"] = {
            "input_norm": {"weight": t(p + "input_layernorm.weight")},
            "q_proj": {"weight": t(p + "self_attn.q_proj.weight")},
            "kv_proj": {"weight": jnp.concatenate(
                [t(p + "self_attn.k_proj.weight"),
                 t(p + "self_attn.v_proj.weight")], axis=0)},
            "o_proj": {"weight": t(p + "self_attn.o_proj.weight")},
            "post_norm": {"weight": t(p + "post_attention_layernorm.weight")},
            "gate_up_proj": {"weight": jnp.concatenate(
                [t(p + "mlp.gate_proj.weight"),
                 t(p + "mlp.up_proj.weight")], axis=0)},
            "down_proj": {"weight": t(p + "mlp.down_proj.weight")},
        }
    # every checkpoint tensor must have landed somewhere: silently dropped
    # weights (e.g. bias tensors) mean silently wrong numerics
    ignorable = {k for k in state_dict
                 if k.endswith("rotary_emb.inv_freq")
                 or (cfg.tie_word_embeddings and k == "lm_head.weight")}
    leftover = set(state_dict) - consumed - ignorable
    if leftover:
        raise ValueError(
            f"unconsumed checkpoint tensors (conversion would silently "
            f"drop them): {sorted(leftover)[:8]}")
    return params


def gpt2_config_from_hf(hf_config):
    """Map a ``transformers.GPT2Config`` to :class:`GPTConfig` (fp32).
    Fails loud on config variants GPTModel does not express."""
    from apex_tpu.models.gpt import GPTConfig

    act = getattr(hf_config, "activation_function", "gelu_new")
    if act != "gelu_new":
        raise NotImplementedError(
            f"activation_function={act!r}: GPTModel hardcodes tanh-GELU "
            "(gelu_new) — converting would silently change the numerics")
    for flag in ("scale_attn_by_inverse_layer_idx",
                 "reorder_and_upcast_attn"):
        if getattr(hf_config, flag, False):
            raise NotImplementedError(
                f"{flag}=True has no GPTModel analog")
    return GPTConfig(
        vocab_size=hf_config.vocab_size,
        hidden_size=hf_config.n_embd,
        num_layers=hf_config.n_layer,
        num_heads=hf_config.n_head,
        max_position_embeddings=hf_config.n_positions,
        layernorm_eps=hf_config.layer_norm_epsilon,
        dtype=jnp.float32,
    )


def gpt2_params_from_hf(state_dict, cfg) -> dict:
    """Convert a ``GPT2LMHeadModel.state_dict()`` into the ``GPTModel``
    param tree. GPT-2's Conv1D weights are (in, out) — transposed to the
    Megatron (out, in) layout; the fused c_attn [q|k|v] column order
    matches our qkv row thirds after the transpose. GPT-2 ties its head
    (``GPTModel`` is always tied), so ``lm_head.weight`` is ignorable."""
    if cfg.tensor_parallel_size != 1:
        raise NotImplementedError(
            "gpt2_params_from_hf emits the tp=1 layout (per-rank qkv needs "
            "per-third interleaving)")
    consumed = set()

    def t(name, transpose=False):
        return _fetch(state_dict, consumed, name, transpose)

    params = {
        "word_embeddings": {"weight": t("transformer.wte.weight")},
        "position_embeddings": t("transformer.wpe.weight"),
        "final_norm": {"weight": t("transformer.ln_f.weight"),
                       "bias": t("transformer.ln_f.bias")},
    }
    for i in range(cfg.num_layers):
        p = f"transformer.h.{i}."
        params[f"layer_{i}"] = {
            "input_norm": {"weight": t(p + "ln_1.weight"),
                           "bias": t(p + "ln_1.bias")},
            "qkv": {"weight": t(p + "attn.c_attn.weight", transpose=True),
                    "bias": t(p + "attn.c_attn.bias")},
            "out_proj": {"weight": t(p + "attn.c_proj.weight",
                                     transpose=True),
                         "bias": t(p + "attn.c_proj.bias")},
            "post_norm": {"weight": t(p + "ln_2.weight"),
                          "bias": t(p + "ln_2.bias")},
            "mlp_in": {"weight": t(p + "mlp.c_fc.weight", transpose=True),
                       "bias": t(p + "mlp.c_fc.bias")},
            "mlp_out": {"weight": t(p + "mlp.c_proj.weight",
                                    transpose=True),
                        "bias": t(p + "mlp.c_proj.bias")},
        }
    ignorable = {k for k in state_dict
                 if k == "lm_head.weight"                     # tied to wte
                 or k.endswith(".attn.bias")                  # causal mask
                 or k.endswith(".attn.masked_bias")}
    leftover = set(state_dict) - consumed - ignorable
    if leftover:
        raise ValueError(
            f"unconsumed checkpoint tensors: {sorted(leftover)[:8]}")
    return params


def bert_config_from_hf(hf_config):
    """Map a ``transformers.BertConfig`` to :class:`BertConfig` (fp32).
    Fails loud on activations the model cannot express."""
    from apex_tpu.models.bert import BertConfig

    act = getattr(hf_config, "hidden_act", "gelu")
    if act not in ("gelu", "gelu_new"):
        raise NotImplementedError(
            f"hidden_act={act!r}: BertForPreTraining supports exact "
            "('gelu') or tanh ('gelu_new') GELU only")
    pet = getattr(hf_config, "position_embedding_type", "absolute")
    if pet != "absolute":
        raise NotImplementedError(
            f"position_embedding_type={pet!r}: only learned absolute "
            "positions are expressed by BertForPreTraining")
    if getattr(hf_config, "is_decoder", False):
        raise NotImplementedError(
            "is_decoder=True (cross-attention BERT) has no analog here")
    return BertConfig(
        vocab_size=hf_config.vocab_size,
        hidden_size=hf_config.hidden_size,
        num_layers=hf_config.num_hidden_layers,
        num_heads=hf_config.num_attention_heads,
        intermediate_size=hf_config.intermediate_size,
        max_position_embeddings=hf_config.max_position_embeddings,
        type_vocab_size=hf_config.type_vocab_size,
        hidden_dropout=hf_config.hidden_dropout_prob,
        attention_dropout=hf_config.attention_probs_dropout_prob,
        layernorm_eps=hf_config.layer_norm_eps,
        gelu_approximate=(act == "gelu_new"),
        dtype=jnp.float32,
    )


def bert_params_from_hf(state_dict, cfg) -> dict:
    """Convert a ``BertForPreTraining.state_dict()`` into the
    ``BertForPreTraining`` (ours) param tree. Our BERT stores (in, out)
    activation-major weights (``x @ W``), so every HF (out, in) linear
    transposes; q/k/v fuse column-wise into ``qkv_weight`` ``[Q|K|V]``.
    The tied MLM decoder weight and its alias bias are ignorable."""
    consumed = set()

    def t(name, transpose=False):
        return _fetch(state_dict, consumed, name, transpose)

    params = {
        "word_embeddings": t("bert.embeddings.word_embeddings.weight"),
        "position_embeddings": t("bert.embeddings.position_embeddings.weight"),
        "token_type_embeddings": t("bert.embeddings.token_type_embeddings.weight"),
        "embedding_norm": {"weight": t("bert.embeddings.LayerNorm.weight"),
                           "bias": t("bert.embeddings.LayerNorm.bias")},
        "pooler_weight": t("bert.pooler.dense.weight", transpose=True),
        "pooler_bias": t("bert.pooler.dense.bias"),
        "mlm_dense_weight": t("cls.predictions.transform.dense.weight",
                              transpose=True),
        "mlm_dense_bias": t("cls.predictions.transform.dense.bias"),
        "mlm_norm": {
            "weight": t("cls.predictions.transform.LayerNorm.weight"),
            "bias": t("cls.predictions.transform.LayerNorm.bias")},
        "mlm_output_bias": t("cls.predictions.bias"),
        "nsp_weight": t("cls.seq_relationship.weight", transpose=True),
        "nsp_bias": t("cls.seq_relationship.bias"),
    }
    for i in range(cfg.num_layers):
        p = f"bert.encoder.layer.{i}."
        params[f"layer_{i}"] = {
            "attention": {
                "qkv_weight": jnp.concatenate(
                    [t(p + "attention.self.query.weight", transpose=True),
                     t(p + "attention.self.key.weight", transpose=True),
                     t(p + "attention.self.value.weight", transpose=True)],
                    axis=1),
                "qkv_bias": jnp.concatenate(
                    [t(p + "attention.self.query.bias"),
                     t(p + "attention.self.key.bias"),
                     t(p + "attention.self.value.bias")]),
                "out_weight": t(p + "attention.output.dense.weight",
                                transpose=True),
                "out_bias": t(p + "attention.output.dense.bias"),
            },
            "attention_norm": {
                "weight": t(p + "attention.output.LayerNorm.weight"),
                "bias": t(p + "attention.output.LayerNorm.bias")},
            "mlp_weight1": t(p + "intermediate.dense.weight",
                             transpose=True),
            "mlp_bias1": t(p + "intermediate.dense.bias"),
            "mlp_weight2": t(p + "output.dense.weight", transpose=True),
            "mlp_bias2": t(p + "output.dense.bias"),
            "mlp_norm": {"weight": t(p + "output.LayerNorm.weight"),
                         "bias": t(p + "output.LayerNorm.bias")},
        }
    ignorable = {k for k in state_dict
                 if k == "cls.predictions.decoder.weight"   # tied to wte
                 or k == "cls.predictions.decoder.bias"     # alias of .bias
                 or k.endswith("position_ids")}
    leftover = set(state_dict) - consumed - ignorable
    if leftover:
        raise ValueError(
            f"unconsumed checkpoint tensors: {sorted(leftover)[:8]}")
    return params


def t5_config_from_hf(hf_config, max_position_embeddings=None):
    """Map a ``transformers.T5Config`` to :class:`T5Config` (fp32). Fails
    loud on variants T5Model does not express.

    ``max_position_embeddings`` caps decoder positions (KV-cache length in
    generation). T5's relative bias has no architectural limit, so the cap
    is ours: default ``hf_config.n_positions`` when present, else 512. Pass
    a larger value for long-output variants (ADVICE r4)."""
    from apex_tpu.models.t5 import T5Config

    if max_position_embeddings is None:
        max_position_embeddings = int(
            getattr(hf_config, "n_positions", None) or 512)
    ff = getattr(hf_config, "feed_forward_proj", "relu")
    if ff not in ("relu", "gated-gelu"):
        raise NotImplementedError(
            f"feed_forward_proj={ff!r}: T5Model implements relu (v1.0) and "
            "gated-gelu (v1.1) only")
    dec_layers = getattr(hf_config, "num_decoder_layers",
                         hf_config.num_layers)
    if dec_layers != hf_config.num_layers:
        raise NotImplementedError(
            f"num_decoder_layers={dec_layers} != num_layers="
            f"{hf_config.num_layers}: T5Model shares one depth")
    return T5Config(
        vocab_size=hf_config.vocab_size,
        d_model=hf_config.d_model,
        d_ff=hf_config.d_ff,
        num_layers=hf_config.num_layers,
        num_heads=hf_config.num_heads,
        head_dim=hf_config.d_kv,
        relative_attention_num_buckets=
            hf_config.relative_attention_num_buckets,
        relative_attention_max_distance=getattr(
            hf_config, "relative_attention_max_distance", 128),
        rms_eps=hf_config.layer_norm_epsilon,
        ff_act=ff,
        dtype=jnp.float32,
        decoder_start_token_id=getattr(
            hf_config, "decoder_start_token_id", 0) or 0,
        tie_word_embeddings=bool(
            getattr(hf_config, "tie_word_embeddings", True)),
        max_position_embeddings=max_position_embeddings,
    )


def t5_params_from_hf(state_dict, cfg) -> dict:
    """Convert a ``T5ForConditionalGeneration.state_dict()`` into the
    ``T5Model`` param tree (tp=1 layout). Fused layouts: self-attn
    ``qkv`` = [Q | K | V] rows, cross-attn ``kv`` = [K | V] rows,
    gated-gelu ``wi`` = [wi_0 | wi_1] rows."""
    if cfg.tensor_parallel_size != 1:
        raise NotImplementedError(
            "t5_params_from_hf emits the tp=1 layout; convert at tp=1 and "
            "slice per rank (fused projections need per-shard interleaving)")
    consumed = set()

    def t(name):
        return _fetch(state_dict, consumed, name)

    def ffn(p):
        if cfg.ff_act == "gated-gelu":
            wi = jnp.concatenate([t(p + "DenseReluDense.wi_0.weight"),
                                  t(p + "DenseReluDense.wi_1.weight")],
                                 axis=0)
        else:
            wi = t(p + "DenseReluDense.wi.weight")
        return {"wi": {"weight": wi},
                "wo": {"weight": t(p + "DenseReluDense.wo.weight")}}

    params = {
        "shared": {"weight": t("shared.weight")},
        "enc_rel_bias": {"rel_attn_bias": t(
            "encoder.block.0.layer.0.SelfAttention"
            ".relative_attention_bias.weight")},
        "dec_rel_bias": {"rel_attn_bias": t(
            "decoder.block.0.layer.0.SelfAttention"
            ".relative_attention_bias.weight")},
        "enc_final_norm": {"weight": t("encoder.final_layer_norm.weight")},
        "dec_final_norm": {"weight": t("decoder.final_layer_norm.weight")},
    }
    if not cfg.tie_word_embeddings:
        params["lm_head"] = {"weight": t("lm_head.weight")}
    for i in range(cfg.num_layers):
        e = f"encoder.block.{i}.layer."
        params[f"enc_{i}"] = {
            "attn_norm": {"weight": t(e + "0.layer_norm.weight")},
            "self_attn": {
                "qkv": {"weight": jnp.concatenate(
                    [t(e + "0.SelfAttention.q.weight"),
                     t(e + "0.SelfAttention.k.weight"),
                     t(e + "0.SelfAttention.v.weight")], axis=0)},
                "out": {"weight": t(e + "0.SelfAttention.o.weight")},
            },
            "ffn_norm": {"weight": t(e + "1.layer_norm.weight")},
            "ffn": ffn(e + "1."),
        }
        d = f"decoder.block.{i}.layer."
        params[f"dec_{i}"] = {
            "attn_norm": {"weight": t(d + "0.layer_norm.weight")},
            "self_attn": {
                "qkv": {"weight": jnp.concatenate(
                    [t(d + "0.SelfAttention.q.weight"),
                     t(d + "0.SelfAttention.k.weight"),
                     t(d + "0.SelfAttention.v.weight")], axis=0)},
                "out": {"weight": t(d + "0.SelfAttention.o.weight")},
            },
            "cross_norm": {"weight": t(d + "1.layer_norm.weight")},
            "cross_attn": {
                "q": {"weight": t(d + "1.EncDecAttention.q.weight")},
                "kv": {"weight": jnp.concatenate(
                    [t(d + "1.EncDecAttention.k.weight"),
                     t(d + "1.EncDecAttention.v.weight")], axis=0)},
                "out": {"weight": t(d + "1.EncDecAttention.o.weight")},
            },
            "ffn_norm": {"weight": t(d + "2.layer_norm.weight")},
            "ffn": ffn(d + "2."),
        }
    # shared-embedding aliases and tied heads are the only legal leftovers
    ignorable = {k for k in state_dict
                 if k in ("encoder.embed_tokens.weight",
                          "decoder.embed_tokens.weight")
                 or (cfg.tie_word_embeddings and k == "lm_head.weight")}
    leftover = set(state_dict) - consumed - ignorable
    if leftover:
        raise ValueError(
            f"unconsumed checkpoint tensors (conversion would silently "
            f"drop them): {sorted(leftover)[:8]}")
    return params
