"""Stage-partitioned GPT: pipeline parallelism on a REAL model, composed
with tensor parallelism.

Reference: apex/transformer/pipeline_parallel is exercised upstream through
Megatron-style models whose layers are divided into contiguous per-stage
blocks, with the embedding on the first stage, the tied LM head on the last,
and the tied-embedding grads all-reduced between them over
``parallel_state._EMBEDDING_GROUP``. This module restates that for the
scan+ppermute schedules: the decoder blocks of ``apex_tpu.models.gpt`` are
split into S stacks, the schedule's ``first_fn`` is the (vocab-parallel)
embedding preprocess, and ``loss_fn`` is the final-norm + tied-head +
vocab-parallel-CE postprocess.

Tied embeddings: every stage's local tree carries the shared params (embed /
pos / final norm); only stage 0 (embed) and the last stage (head) produce
nonzero grads for them, so ``psum`` of the shared-grad subtree over the
stage axis reproduces the reference's embedding all-reduce exactly —
the ``.sum(0)`` over shared grads inside ``merge_pipeline_grads`` does this.
"""

from __future__ import annotations

import dataclasses
from typing import Tuple

import jax
import jax.numpy as jnp
from jax import lax

from apex_tpu.amp.policy import resolve_compute_dtype
from apex_tpu.mesh import CONTEXT_AXIS, MODEL_AXIS, STAGE_AXIS
from apex_tpu.models.gpt import GPTConfig, GPTModel, ParallelDecoderBlock
from apex_tpu.normalization import FusedLayerNorm
from apex_tpu.transformer.tensor_parallel import (
    VocabParallelEmbedding,
    vocab_parallel_cross_entropy,
)
from apex_tpu.transformer.tensor_parallel.mappings import axis_is_bound
from apex_tpu.transformer.utils import divide


GPT_SHARED_NAMES = ("word_embeddings", "position_embeddings", "final_norm")


def split_params_for_pipeline(params, n_stages: int, num_layers: int,
                              shared_names, virtual_chunks: int = 1):
    """Partition a layer_i-structured param tree into the pipeline layout
    (model-agnostic core; GPT/Llama wrappers below fix ``shared_names``).

    Returns a pytree whose leaves are stacked ``[n_stages, ...]`` for use
    with ``shard_map(in_specs=P(STAGE_AXIS))``:

      {"blocks": [S, V, K, ...] per-stage chunk-stacked decoder blocks,
       "shared": [S, ...] the ``shared_names`` params REPLICATED to every
                 stage (tied-embedding layout)}

    With ``virtual_chunks=V>1``, stage s's chunk v holds global layers of
    virtual stage ``v*S + s`` (Megatron's round-robin VPP assignment).
    """
    chunk_layers = divide(num_layers, n_stages * virtual_chunks)

    def stack_layers(idxs):
        trees = [params[f"layer_{i}"] for i in idxs]
        return jax.tree.map(lambda *xs: jnp.stack(xs), *trees)

    blocks = []
    for s in range(n_stages):
        chunks = []
        for v in range(virtual_chunks):
            vs = v * n_stages + s      # global virtual stage index
            start = vs * chunk_layers
            chunks.append(stack_layers(range(start, start + chunk_layers)))
        blocks.append(jax.tree.map(lambda *xs: jnp.stack(xs), *chunks))
    blocks = jax.tree.map(lambda *xs: jnp.stack(xs), *blocks)

    shared = {name: params[name] for name in shared_names}
    shared = jax.tree.map(
        lambda x: jnp.broadcast_to(x[None], (n_stages,) + x.shape), shared)
    return {"blocks": blocks, "shared": shared}


def merge_pipeline_grads(grads, n_stages: int, num_layers: int,
                         shared_names, virtual_chunks: int = 1):
    """Inverse of ``split_params_for_pipeline`` for STACKED grad trees
    (leaves ``[S, ...]``): reassembles a model-layout grad tree, summing
    the shared-param grads over stages (the tied-embedding all-reduce)."""
    chunk_layers = divide(num_layers, n_stages * virtual_chunks)
    out = {}
    for s in range(n_stages):
        for v in range(virtual_chunks):
            vs = v * n_stages + s
            for k in range(chunk_layers):
                out[f"layer_{vs * chunk_layers + k}"] = jax.tree.map(
                    lambda t, s=s, v=v, k=k: t[s, v, k], grads["blocks"])
    for name in shared_names:
        out[name] = jax.tree.map(lambda t: t.sum(0), grads["shared"][name])
    return out


def split_gpt_params_for_pipeline(params, n_stages: int, num_layers: int,
                                  virtual_chunks: int = 1):
    """GPT layout: see ``split_params_for_pipeline``."""
    return split_params_for_pipeline(params, n_stages, num_layers,
                                     GPT_SHARED_NAMES, virtual_chunks)


def merge_pipeline_grads_to_gpt(grads, n_stages: int, num_layers: int,
                                virtual_chunks: int = 1):
    """GPT layout: see ``merge_pipeline_grads``."""
    return merge_pipeline_grads(grads, n_stages, num_layers,
                                GPT_SHARED_NAMES, virtual_chunks)


def make_gpt_pipeline_fns(cfg: GPTConfig) -> Tuple:
    """(first_fn, stage_fn, loss_fn) for the pipeline schedules.

    ``first_fn(local, ids)`` — vocab-parallel embed + positions (stage-0
    preprocess); ``stage_fn(local, x)`` — this stage's decoder blocks via
    ``lax.scan`` over the stacked block params; ``loss_fn(local, y, labels)``
    — final norm + tied LM head + vocab-parallel CE (last-stage
    postprocess). Use with ``loss_with_params=True``.

    The ``local`` tree is one device's slice: ``{"blocks": [V?, K, ...],
    "shared": {...}}`` (chunk axis present only under VPP).
    """
    if cfg.num_experts > 0:
        # the scanned shared-block formulation can't express per-layer MoE
        # selection, and block.apply here discards sown aux losses — fail
        # loud rather than train without load balancing
        raise NotImplementedError(
            "pipeline stages do not support MoE blocks yet "
            "(num_experts > 0); use the non-pipelined GPTModel")
    tp = cfg.tensor_parallel_size
    emb = VocabParallelEmbedding(cfg.vocab_size, cfg.hidden_size,
                                 world_size=tp, params_dtype=cfg.param_dtype)
    block = ParallelDecoderBlock(cfg)
    norm = FusedLayerNorm(cfg.hidden_size, eps=cfg.layernorm_eps)

    def _cp_bound():
        return cfg.context_parallel and axis_is_bound(CONTEXT_AXIS)

    def first_fn(local, ids):
        sh = local["shared"]
        x = emb.apply({"params": sh["word_embeddings"]}, ids)
        s = ids.shape[-1]
        if _cp_bound():
            # sequence sharded over ``context``: chunk i holds global
            # positions [i*s, (i+1)*s) (mirrors GPTModel's CP path)
            cp = lax.axis_size(CONTEXT_AXIS)
            if cp * s > cfg.max_position_embeddings:
                # dynamic_slice would CLAMP an out-of-range start and
                # silently reuse positions on late ranks
                raise ValueError(
                    f"global sequence cp*s = {cp}*{s} exceeds "
                    f"max_position_embeddings={cfg.max_position_embeddings}")
            off = lax.axis_index(CONTEXT_AXIS) * s
            pos = lax.dynamic_slice_in_dim(sh["position_embeddings"], off, s)
        else:
            pos = sh["position_embeddings"][:s]
        x = x + pos[None, :, :]
        # amp O1 seam: same cast as the dense GPTModel
        return x.astype(resolve_compute_dtype(cfg.dtype))

    # cfg.remat: recompute each block in backward (jax.checkpoint on the
    # PURE block.apply — no flax scoping involved), bounding within-stage
    # residuals; the 1F1B schedule already rematerializes whole stages
    # from their saved inputs, so this nests per-block inside that
    block_apply = (jax.checkpoint(block.apply) if cfg.remat
                   else block.apply)

    def stage_fn(local, x):
        def body(h, bp):
            return block_apply({"params": bp}, h), None

        h, _ = lax.scan(body, x, local["blocks"])
        return h

    def loss_fn(local, y, labels):
        sh = local["shared"]
        h = norm.apply({"params": sh["final_norm"]}, y)
        logits = emb.apply({"params": sh["word_embeddings"]},
                           h.astype(resolve_compute_dtype(cfg.dtype)),
                           method=VocabParallelEmbedding.attend)
        if axis_is_bound(MODEL_AXIS):
            per_tok = vocab_parallel_cross_entropy(
                logits.astype(jnp.float32), labels, axis_name=MODEL_AXIS)
        else:
            logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
            per_tok = -jnp.take_along_axis(
                logp, labels[..., None], axis=-1)[..., 0]
        loss = per_tok.mean()
        if _cp_bound():
            # chunk means combine to the global token mean (equal chunks)
            loss = lax.pmean(loss, CONTEXT_AXIS)
        return loss

    return first_fn, stage_fn, loss_fn
