"""Stage-partitioned GPT: pipeline parallelism on a REAL model, composed
with tensor parallelism.

Reference: apex/transformer/pipeline_parallel is exercised upstream through
Megatron-style models whose layers are divided into contiguous per-stage
blocks, with the embedding on the first stage, the tied LM head on the last,
and the tied-embedding grads all-reduced between them over
``parallel_state._EMBEDDING_GROUP``. This module restates that for the
scan+ppermute schedules: the decoder blocks of ``apex_tpu.models.gpt`` are
split into S stacks, the schedule's ``first_fn`` is the (vocab-parallel)
embedding preprocess, and ``loss_fn`` is the final-norm + tied-head +
vocab-parallel-CE postprocess.

Tied embeddings: every stage's local tree carries the shared params (embed /
pos / final norm); only stage 0 (embed) and the last stage (head) produce
nonzero grads for them, so ``psum`` of the shared-grad subtree over the
stage axis reproduces the reference's embedding all-reduce exactly —
the ``.sum(0)`` over shared grads inside ``merge_pipeline_grads`` does this.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax import lax

from apex_tpu.amp.policy import resolve_compute_dtype
from apex_tpu.mesh import CONTEXT_AXIS, MODEL_AXIS, STAGE_AXIS
from apex_tpu.models.gpt import GPTConfig, GPTModel, ParallelDecoderBlock
from apex_tpu.normalization import FusedLayerNorm
from apex_tpu.transformer.tensor_parallel import (
    VocabParallelEmbedding,
)
from apex_tpu.transformer.tensor_parallel.mappings import axis_is_bound
from apex_tpu.transformer.utils import divide


GPT_SHARED_NAMES = ("word_embeddings", "position_embeddings", "final_norm")


def is_per_position_layout(blocks_tree) -> bool:
    """Exact detection of the heterogeneous per-position block layout the
    split writes: a dict keyed ``k0..k{K-1}`` (one entry per within-stage
    layer position). The scanned layout is a dict of PARAM names instead."""
    return (isinstance(blocks_tree, dict)
            and set(blocks_tree) == {f"k{i}"
                                     for i in range(len(blocks_tree))})


def split_params_for_pipeline(params, n_stages: int, num_layers: int,
                              shared_names, virtual_chunks: int = 1):
    """Partition a layer_i-structured param tree into the pipeline layout
    (model-agnostic core; GPT/Llama wrappers below fix ``shared_names``).

    Returns a pytree whose leaves are stacked ``[n_stages, ...]`` for use
    with ``shard_map(in_specs=P(STAGE_AXIS))``:

      {"blocks": [S, V, K, ...] per-stage chunk-stacked decoder blocks,
       "shared": [S, ...] the ``shared_names`` params REPLICATED to every
                 stage (tied-embedding layout)}

    With ``virtual_chunks=V>1``, stage s's chunk v holds global layers of
    virtual stage ``v*S + s`` (Megatron's round-robin VPP assignment).
    """
    chunk_layers = divide(num_layers, n_stages * virtual_chunks)
    structs = [jax.tree_util.tree_structure(params[f"layer_{i}"])
               for i in range(num_layers)]
    homogeneous = all(st == structs[0] for st in structs)

    if not homogeneous:
        # heterogeneous layers (MoE every Nth block): per-POSITION dict
        # layout {"k0": tree, "k1": tree, ...} — positions keep their own
        # structure, leaves stack over stages only. Stage-position k must
        # have the SAME structure on every stage (SPMD runs one program),
        # which holds iff the MoE stride divides the layers-per-stage —
        # the split itself verifies it structurally below.
        if virtual_chunks != 1:
            raise NotImplementedError(
                "virtual pipeline chunks with heterogeneous (MoE) layers "
                "are not supported; use virtual_chunks=1")
        per_stage = []
        for s in range(n_stages):
            per_stage.append({
                f"k{k}": params[f"layer_{s * chunk_layers + k}"]
                for k in range(chunk_layers)})
        for s in range(1, n_stages):
            for k in range(chunk_layers):
                if (jax.tree_util.tree_structure(per_stage[s][f"k{k}"])
                        != jax.tree_util.tree_structure(
                            per_stage[0][f"k{k}"])):
                    raise NotImplementedError(
                        "per-stage layer structures differ (the MoE stride "
                        "does not divide layers-per-stage); choose "
                        "moe_layer_freq so it divides "
                        f"{chunk_layers} layers/stage")
        blocks = jax.tree.map(lambda *xs: jnp.stack(xs), *per_stage)
        shared = {name: params[name] for name in shared_names}
        shared = jax.tree.map(
            lambda x: jnp.broadcast_to(x[None], (n_stages,) + x.shape),
            shared)
        return {"blocks": blocks, "shared": shared}

    def stack_layers(idxs):
        trees = [params[f"layer_{i}"] for i in idxs]
        return jax.tree.map(lambda *xs: jnp.stack(xs), *trees)

    blocks = []
    for s in range(n_stages):
        chunks = []
        for v in range(virtual_chunks):
            vs = v * n_stages + s      # global virtual stage index
            start = vs * chunk_layers
            chunks.append(stack_layers(range(start, start + chunk_layers)))
        blocks.append(jax.tree.map(lambda *xs: jnp.stack(xs), *chunks))
    blocks = jax.tree.map(lambda *xs: jnp.stack(xs), *blocks)

    shared = {name: params[name] for name in shared_names}
    shared = jax.tree.map(
        lambda x: jnp.broadcast_to(x[None], (n_stages,) + x.shape), shared)
    return {"blocks": blocks, "shared": shared}


def merge_pipeline_grads(grads, n_stages: int, num_layers: int,
                         shared_names, virtual_chunks: int = 1):
    """Inverse of ``split_params_for_pipeline`` for STACKED grad trees
    (leaves ``[S, ...]``): reassembles a model-layout grad tree, summing
    the shared-param grads over stages (the tied-embedding all-reduce).
    Handles both block layouts (scanned layer-stacked and the
    heterogeneous per-position ``k<i>`` dicts — see the split)."""
    chunk_layers = divide(num_layers, n_stages * virtual_chunks)
    out = {}
    blocks = grads["blocks"]
    het = is_per_position_layout(blocks)
    for s in range(n_stages):
        for v in range(virtual_chunks):
            vs = v * n_stages + s
            for k in range(chunk_layers):
                if het:
                    out[f"layer_{vs * chunk_layers + k}"] = jax.tree.map(
                        lambda t, s=s: t[s], blocks[f"k{k}"])
                else:
                    out[f"layer_{vs * chunk_layers + k}"] = jax.tree.map(
                        lambda t, s=s, v=v, k=k: t[s, v, k], blocks)
    for name in shared_names:
        out[name] = jax.tree.map(lambda t: t.sum(0), grads["shared"][name])
    return out


def split_gpt_params_for_pipeline(params, n_stages: int, num_layers: int,
                                  virtual_chunks: int = 1):
    """GPT layout: see ``split_params_for_pipeline``."""
    return split_params_for_pipeline(params, n_stages, num_layers,
                                     GPT_SHARED_NAMES, virtual_chunks)


def merge_pipeline_grads_to_gpt(grads, n_stages: int, num_layers: int,
                                virtual_chunks: int = 1):
    """GPT layout: see ``merge_pipeline_grads``."""
    return merge_pipeline_grads(grads, n_stages, num_layers,
                                GPT_SHARED_NAMES, virtual_chunks)


def make_gpt_pipeline_fns(cfg: GPTConfig) -> Tuple:
    """(first_fn, stage_fn, loss_fn) for the pipeline schedules.

    ``first_fn(local, ids)`` — vocab-parallel embed + positions (stage-0
    preprocess); ``stage_fn(local, x)`` — this stage's decoder blocks via
    ``lax.scan`` over the stacked block params; ``loss_fn(local, y, labels)``
    — final norm + tied LM head + vocab-parallel CE (last-stage
    postprocess). Use with ``loss_with_params=True``.

    The ``local`` tree is one device's slice: ``{"blocks": [V?, K, ...],
    "shared": {...}}`` (chunk axis present only under VPP).
    """
    moe = cfg.num_experts > 0
    tp = cfg.tensor_parallel_size
    emb = VocabParallelEmbedding(cfg.vocab_size, cfg.hidden_size,
                                 world_size=tp, params_dtype=cfg.param_dtype)
    block = ParallelDecoderBlock(cfg)
    norm = FusedLayerNorm(cfg.hidden_size, eps=cfg.layernorm_eps)

    def _cp_bound():
        return cfg.context_parallel and axis_is_bound(CONTEXT_AXIS)

    def first_fn(local, ids):
        sh = local["shared"]
        x = emb.apply({"params": sh["word_embeddings"]}, ids)
        s = ids.shape[-1]
        if _cp_bound():
            # sequence sharded over ``context``: chunk i holds global
            # positions [i*s, (i+1)*s) (mirrors GPTModel's CP path)
            cp = lax.axis_size(CONTEXT_AXIS)
            if cp * s > cfg.max_position_embeddings:
                # dynamic_slice would CLAMP an out-of-range start and
                # silently reuse positions on late ranks
                raise ValueError(
                    f"global sequence cp*s = {cp}*{s} exceeds "
                    f"max_position_embeddings={cfg.max_position_embeddings}")
            off = lax.axis_index(CONTEXT_AXIS) * s
            pos = lax.dynamic_slice_in_dim(sh["position_embeddings"], off, s)
        else:
            pos = sh["position_embeddings"][:s]
        x = x + pos[None, :, :]
        # amp O1 seam: same cast as the dense GPTModel
        x = x.astype(resolve_compute_dtype(cfg.dtype))
        if moe:
            # MoE payload: the running aux-loss scalar rides the pipeline
            # with the activation (pytree payloads are autodiff-schedule
            # only — the dispatcher routes them there)
            return (x, jnp.zeros((), jnp.float32))
        return x

    # cfg.remat: recompute each block in backward (jax.checkpoint on the
    # PURE block.apply — no flax scoping involved), bounding within-stage
    # residuals; the 1F1B schedule already rematerializes whole stages
    # from their saved inputs, so this nests per-block inside that
    block_apply = (jax.checkpoint(block.apply) if cfg.remat
                   else block.apply)

    def stage_fn(local, payload):
        if not moe:
            def body(h, bp):
                return block_apply({"params": bp}, h), None

            h, _ = lax.scan(body, payload, local["blocks"])
            return h

        from apex_tpu.transformer.moe import collect_sown_aux

        h, aux = payload
        blocks_tree = local["blocks"]
        if not is_per_position_layout(blocks_tree):
            # homogeneous MoE (moe_layer_freq=1: every block routed, or a
            # stride selecting none): the split kept the scanned layout —
            # scan with the aux in the carry (mutable returns {} for
            # non-routed blocks, collect yields 0). ``mutable`` is bound
            # BEFORE jax.checkpoint: it is a static kwarg, not a tracer.
            apply_m = functools.partial(block.apply,
                                        mutable=["intermediates"])
            if cfg.remat:
                apply_m = jax.checkpoint(apply_m)

            def body(carry, bp):
                hh, ax = carry
                out, upd = apply_m({"params": bp}, hh)
                return (out, ax + collect_sown_aux(upd)), None

            (h, aux), _ = lax.scan(body, (h, aux), blocks_tree)
            return h, aux

        # heterogeneous per-position layout (split_params_for_pipeline):
        # python loop over the K within-stage positions; position k's
        # MoE-vs-dense choice is stage-uniform (the split verified the
        # stride divides layers/stage), so layer_idx=k selects correctly
        for key in sorted(blocks_tree, key=lambda n: int(n[1:])):
            blk = ParallelDecoderBlock(cfg, layer_idx=int(key[1:]))
            if blk._is_moe_layer():
                apply_k = functools.partial(blk.apply,
                                            mutable=["intermediates"])
                if cfg.remat:
                    apply_k = jax.checkpoint(apply_k)
                h, upd = apply_k({"params": blocks_tree[key]}, h)
                aux = aux + collect_sown_aux(upd)
            else:
                apply_k = (jax.checkpoint(blk.apply) if cfg.remat
                           else blk.apply)
                h = apply_k({"params": blocks_tree[key]}, h)
        return h, aux

    def loss_fn(local, y, labels):
        from apex_tpu.models.gpt import lm_token_loss

        sh = local["shared"]
        moe_aux = None
        if moe:
            y, moe_aux = y
        h = norm.apply({"params": sh["final_norm"]}, y)
        logits = emb.apply({"params": sh["word_embeddings"]},
                           h.astype(resolve_compute_dtype(cfg.dtype)),
                           method=VocabParallelEmbedding.attend)
        return lm_token_loss(logits, labels, axis_name=MODEL_AXIS,
                             context_parallel=cfg.context_parallel,
                             extra=moe_aux)

    return first_fn, stage_fn, loss_fn
