"""Stage-partitioned Llama: pipeline parallelism for the second model
family, composed with TP (and CP) exactly like gpt_pipeline.py.

Same stage contract as the GPT composition (reference:
apex/transformer/pipeline_parallel exercised through Megatron models):
embedding preprocess on stage 0, the untied LM head + final RMSNorm on the
last stage, decoder blocks scanned per stage. RoPE cos/sin tables are NOT
parameters — each stage recomputes them from the config (with the CP
position offset), so activations crossing stage boundaries stay a single
[B, S, E] tensor.

Shared-param layout: ``embed_tokens`` / ``final_norm`` / ``lm_head`` ride
replicated on every stage ("shared" subtree); only the stages that use them
produce nonzero grads, and ``merge_pipeline_grads`` sums over stages (for
``tie_word_embeddings=True`` the embed grad gets contributions from both
ends — the reference's embedding all-reduce).

Known layout cost: the shard_map-over-``stage`` formulation requires one
HOMOGENEOUS local tree per stage, so the untied ``lm_head`` (and the
embedding) are replicated to stages that never touch them — at vocab 32k /
hidden 4k that is ~125 MB fp32 per matrix per stage of idle HBM. The
replicas cost no compute (zero grads sum away), and at large pp either tie
the embeddings (one shared matrix) or shard the head over ``model`` (TP
already divides it by tp). A per-stage-heterogeneous layout would need the
schedules to drop the single-tree contract — deliberately not done.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
from jax import lax

from apex_tpu.amp.policy import resolve_compute_dtype
from apex_tpu.mesh import CONTEXT_AXIS, MODEL_AXIS
from apex_tpu.models.gpt import lm_token_loss
from apex_tpu.models.gpt_pipeline import (merge_pipeline_grads,
                                          split_params_for_pipeline)
from apex_tpu.models.llama import (LlamaConfig, LlamaDecoderBlock,
                                   _rope_cos_sin)
from apex_tpu.normalization import FusedRMSNorm
from apex_tpu.transformer.tensor_parallel import (ColumnParallelLinear,
                                                  VocabParallelEmbedding)
from apex_tpu.transformer.tensor_parallel.mappings import axis_is_bound


def llama_shared_names(cfg: LlamaConfig):
    names = ["embed_tokens", "final_norm"]
    if not cfg.tie_word_embeddings:
        names.append("lm_head")
    return tuple(names)


def split_llama_params_for_pipeline(cfg: LlamaConfig, params, n_stages: int,
                                    virtual_chunks: int = 1):
    return split_params_for_pipeline(params, n_stages, cfg.num_layers,
                                     llama_shared_names(cfg), virtual_chunks)


def merge_pipeline_grads_to_llama(cfg: LlamaConfig, grads, n_stages: int,
                                  virtual_chunks: int = 1):
    return merge_pipeline_grads(grads, n_stages, cfg.num_layers,
                                llama_shared_names(cfg), virtual_chunks)


def make_llama_pipeline_fns(cfg: LlamaConfig) -> Tuple:
    """(first_fn, stage_fn, loss_fn) for the pipeline schedules
    (use with ``loss_with_params=True``), mirroring make_gpt_pipeline_fns."""
    moe = cfg.num_experts > 0
    tp = cfg.tensor_parallel_size
    emb = VocabParallelEmbedding(cfg.vocab_size, cfg.hidden_size,
                                 world_size=tp, params_dtype=cfg.param_dtype)
    block = LlamaDecoderBlock(cfg)
    norm = FusedRMSNorm(cfg.hidden_size, eps=cfg.rms_eps)
    head = ColumnParallelLinear(cfg.hidden_size, cfg.vocab_size, bias=False,
                                gather_output=False, world_size=tp,
                                params_dtype=cfg.param_dtype)

    def _cp_bound():
        return cfg.context_parallel and axis_is_bound(CONTEXT_AXIS)

    def _tables(s: int):
        if _cp_bound():
            cp = lax.axis_size(CONTEXT_AXIS)
            offset = lax.axis_index(CONTEXT_AXIS) * s
        else:
            cp, offset = 1, 0
        if cp * s > cfg.max_position_embeddings:
            raise ValueError(
                f"global sequence cp*s = {cp}*{s} exceeds "
                f"max_position_embeddings={cfg.max_position_embeddings}")
        return _rope_cos_sin(cfg, s, offset)

    def first_fn(local, ids):
        x = emb.apply({"params": local["shared"]["embed_tokens"]}, ids)
        # amp O1 seam: same cast as the dense LlamaModel
        x = x.astype(resolve_compute_dtype(cfg.dtype))
        if moe:
            # aux-loss scalar rides the payload (autodiff schedule only —
            # the dispatcher routes pytree payloads there)
            return (x, jnp.zeros((), jnp.float32))
        return x

    # cfg.remat: per-block recompute inside the stage (see gpt_pipeline)
    block_apply = (jax.checkpoint(block.apply) if cfg.remat
                   else block.apply)

    def stage_fn(local, payload):
        if not moe:
            cos_, sin_ = _tables(payload.shape[-2])

            def body(h, bp):
                return block_apply({"params": bp}, h, cos_, sin_), None

            h, _ = lax.scan(body, payload, local["blocks"])
            return h

        import functools

        from apex_tpu.models.gpt_pipeline import is_per_position_layout
        from apex_tpu.models.llama import LlamaDecoderBlock as _Blk
        from apex_tpu.transformer.moe import collect_sown_aux

        h, aux = payload
        cos_, sin_ = _tables(h.shape[-2])
        blocks_tree = local["blocks"]
        if not is_per_position_layout(blocks_tree):
            # homogeneous MoE (freq=1 all routed / stride selecting none):
            # scanned layout, aux in the carry; mutable bound pre-checkpoint
            apply_m = functools.partial(block.apply,
                                        mutable=["intermediates"])
            if cfg.remat:
                apply_m = jax.checkpoint(apply_m)

            def body(carry, bp):
                hh, ax = carry
                out, upd = apply_m({"params": bp}, hh, cos_, sin_)
                return (out, ax + collect_sown_aux(upd)), None

            (h, aux), _ = lax.scan(body, (h, aux), blocks_tree)
            return h, aux

        # heterogeneous per-position layout (see gpt_pipeline.stage_fn)
        for key in sorted(blocks_tree, key=lambda n: int(n[1:])):
            blk = _Blk(cfg, layer_idx=int(key[1:]))
            if blk._is_moe_layer():
                apply_k = functools.partial(blk.apply,
                                            mutable=["intermediates"])
                if cfg.remat:
                    apply_k = jax.checkpoint(apply_k)
                h, upd = apply_k({"params": blocks_tree[key]}, h, cos_,
                                 sin_)
                aux = aux + collect_sown_aux(upd)
            else:
                apply_k = (jax.checkpoint(blk.apply) if cfg.remat
                           else blk.apply)
                h = apply_k({"params": blocks_tree[key]}, h, cos_, sin_)
        return h, aux

    def loss_fn(local, y, labels):
        sh = local["shared"]
        moe_aux = None
        if moe:
            y, moe_aux = y
        h = norm.apply({"params": sh["final_norm"]}, y).astype(
            resolve_compute_dtype(cfg.dtype))
        if cfg.tie_word_embeddings:
            logits = emb.apply({"params": sh["embed_tokens"]}, h,
                               method=VocabParallelEmbedding.attend)
        else:
            logits = head.apply({"params": sh["lm_head"]}, h)
        return lm_token_loss(logits, labels, axis_name=MODEL_AXIS,
                             context_parallel=cfg.context_parallel,
                             extra=moe_aux)

    return first_fn, stage_fn, loss_fn
