"""Megatron-style tensor-parallel GPT (BASELINE.md config #4).

Reference: apex/transformer/testing/standalone_gpt.py (test-only vendored
Megatron GPT driving the TP layers) — here a first-class model: pre-LN
decoder blocks whose QKV/out-proj and MLP are ColumnParallel/RowParallel
linears, VocabParallelEmbedding + vocab-parallel cross entropy, causal
Pallas flash attention on the LOCAL head shard (heads divide over the
``model`` axis, the Megatron attention-head split).

Runs inside ``shard_map`` with the ``model`` axis bound (TP>1) or plain
(TP=1, collectives degrade to identity via the layers' axis guards).
"""

from __future__ import annotations

import dataclasses
from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax import lax

from apex_tpu.amp.policy import resolve_compute_dtype
from apex_tpu.mesh import CONTEXT_AXIS, MODEL_AXIS
from apex_tpu.models.generation import (advance_cache, cached_attention,
                                        check_chunk_bounds, is_paged,
                                        is_static_prefill, layer_cache,
                                        update_layer_cache,
                                        update_paged_layer_cache)
from apex_tpu.normalization import FusedLayerNorm
from apex_tpu.ops import (flash_attention, ring_attention,
                          ring_attention_zigzag)
from apex_tpu.transformer.tensor_parallel import (
    ColumnParallelLinear,
    RowParallelLinear,
    VocabParallelEmbedding,
    vocab_parallel_cross_entropy,
)
from apex_tpu.transformer.tensor_parallel.mappings import (
    axis_is_bound as _axis_bound,
)
from apex_tpu.transformer.utils import divide


@dataclasses.dataclass(frozen=True)
class GPTConfig:
    vocab_size: int = 50304          # GPT-2 50257 rounded to lane multiple
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    max_position_embeddings: int = 1024
    layernorm_eps: float = 1e-5
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    tensor_parallel_size: int = 1    # static tp world for shard shapes
    # context parallelism is an explicit OPT-IN: the ``context`` axis being
    # bound only proves the mesh has it, not that the caller sharded the
    # sequence over it (a replicated sequence under a cp>1 mesh would get
    # wrong position offsets and double-counted ring keys)
    context_parallel: bool = False
    # zigzag CP layout (causal load balancing) — caller feeds ids/labels
    # zigzag-permuted along the sequence (ops/ring_attention.py to_zigzag);
    # position embeddings follow the layout automatically
    context_parallel_zigzag: bool = False
    # --- mixture-of-experts (beyond reference) -------------------------------
    # num_experts > 0 turns every ``moe_layer_freq``-th block's MLP into a
    # routed MoEMLP (apex_tpu.transformer.moe). ``expert_parallel`` is the
    # same explicit opt-in discipline as context_parallel: it asserts the
    # caller runs inside shard_map with tokens SHARDED over ``data`` so the
    # experts can shard over that axis (ep = data axis size). Experts are
    # replicated across TP ranks by default (each model rank runs the
    # identical MoE — redundant but consistent); MoEMLP's opt-in
    # tensor_world_size shards the experts' FFN dim over ``model``.
    num_experts: int = 0
    moe_layer_freq: int = 2          # every Nth block (1 = all blocks)
    moe_k: int = 2
    moe_capacity_factor: float = 1.25
    moe_aux_loss_coeff: float = 1e-2
    moe_z_loss_coeff: float = 0.0    # ST-MoE router z-loss
    expert_parallel: bool = False
    # quantized weight streaming (ops/quant.py): block linears store
    # narrow weights + scales and run the fused dequant-matmul kernel.
    # Inference-only — embeddings/norms/biases/tied head stay fp; convert
    # a trained checkpoint with models/quantize.quantize_model_params.
    # Does not compose with MoE (expert weights would silently stay fp —
    # the model raises). ``quantize_int8`` is the back-compat alias for
    # the int8-everywhere policy; ``weight_policy`` picks the per-layer-
    # class precision (WeightPrecisionPolicy: int8 / fp8 / int4-grouped)
    quantize_int8: bool = False
    weight_policy: Any = None            # Optional[WeightPrecisionPolicy]
    # activation rematerialization: recompute each decoder block in
    # backward instead of saving its activations (flax nn.remat, the
    # lifted jax.checkpoint; in pipeline stages: jax.checkpoint around the
    # scanned block apply) — the reference's
    # activations_checkpoint_method="uniform" with one block per chunk;
    # trades ~1/3 more FLOPs for O(layers) less activation HBM
    remat: bool = False

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_heads

    def weight_quant(self):
        """The resolved ``WeightPrecisionPolicy`` (or None for fp
        serving) — the ONE seam the block linears read their precision
        from (named error on a quantize_int8/weight_policy conflict)."""
        from apex_tpu.ops.quant import WeightPrecisionPolicy

        return WeightPrecisionPolicy.resolve(self.weight_policy,
                                             self.quantize_int8)


def gpt2_small_config(**overrides) -> GPTConfig:
    return dataclasses.replace(GPTConfig(), **overrides)


def gpt_tiny_config(**overrides) -> GPTConfig:
    base = GPTConfig(vocab_size=128, hidden_size=64, num_layers=2,
                     num_heads=4, max_position_embeddings=128,
                     dtype=jnp.float32)
    return dataclasses.replace(base, **overrides)


class ParallelDecoderBlock(nn.Module):
    """Pre-LN block: LN -> TP attention -> residual -> LN -> TP MLP -> res.

    With ``config.num_experts > 0`` and this block's ``layer_idx`` selected
    by ``moe_layer_freq``, the MLP is a routed ``MoEMLP``; its aux loss is
    sown into the ``intermediates`` collection (``gpt_loss`` collects it).
    """

    config: GPTConfig
    layer_idx: int = 0

    def _is_moe_layer(self) -> bool:
        from apex_tpu.transformer.moe import moe_layer_selected

        return moe_layer_selected(self.config, self.layer_idx)

    @nn.compact
    def __call__(self, x, cache=None):
        cfg = self.config
        dt = resolve_compute_dtype(cfg.dtype)  # amp O1 seam
        tp = cfg.tensor_parallel_size
        e = cfg.hidden_size
        h_local = divide(cfg.num_heads, tp)
        d = cfg.head_dim
        b, s, _ = x.shape

        pol = cfg.weight_quant()
        qmode = pol.linears if pol else False
        qgs = pol.group_size if pol else 128

        h = FusedLayerNorm(e, eps=cfg.layernorm_eps, name="input_norm")(x)
        h = h.astype(dt)
        # QKV column-parallel: local output is the local heads' q,k,v
        qkv = ColumnParallelLinear(
            e, 3 * e, gather_output=False, world_size=tp,
            params_dtype=cfg.param_dtype, quantize=qmode,
            quantize_group_size=qgs, name="qkv")(h)
        q, k, v = jnp.split(qkv, 3, axis=-1)

        def to_bhsd(t):
            return t.reshape(b, s, h_local, d).transpose(0, 2, 1, 3)

        if cache is not None and is_paged(cache):
            # paged serving decode (apex_tpu/serving): write this step's
            # s-token K/V block into the slot's current pages, then
            # gather-attend over the block table with the Pallas paged
            # kernel (s=1 plain decode, s=k speculative verify, s-sized
            # interleaved-prefill chunks). Monolithic prefill still rides
            # the contiguous flash path and scatters into pages.
            from apex_tpu.ops.paged_attention import paged_attention

            cache = update_paged_layer_cache(cache, to_bhsd(k), to_bhsd(v))
            ctx = paged_attention(to_bhsd(q), cache["k_pages"],
                                  cache["v_pages"], cache["block_tables"],
                                  cache["len"] + s,
                                  k_scales=cache.get("k_scales"),
                                  v_scales=cache.get("v_scales"))
        elif cache is not None:
            # incremental decoding: append this chunk's K/V into the static
            # per-layer cache; a trace-time-provable prefill (static len 0)
            # attends with the training flash kernel (O(tile) memory),
            # decode steps with the masked dot-product over the buffer

            prefill = is_static_prefill(cache, s)
            cache = update_layer_cache(cache, to_bhsd(k), to_bhsd(v))
            if prefill:
                ctx = flash_attention(to_bhsd(q), to_bhsd(k), to_bhsd(v),
                                      causal=True)
            else:
                ctx = cached_attention(to_bhsd(q), cache)
        # context parallelism (beyond reference): with the sequence sharded
        # over ``context``, K/V ring-rotate between devices instead of any
        # device materializing the full sequence (ops/ring_attention.py)
        elif cfg.context_parallel and _axis_bound(CONTEXT_AXIS):
            if cfg.context_parallel_zigzag:
                ctx = ring_attention_zigzag(
                    to_bhsd(q), to_bhsd(k), to_bhsd(v),
                    axis_name=CONTEXT_AXIS)
            else:
                ctx = ring_attention(to_bhsd(q), to_bhsd(k), to_bhsd(v),
                                     axis_name=CONTEXT_AXIS, causal=True)
        else:
            ctx = flash_attention(to_bhsd(q), to_bhsd(k), to_bhsd(v),
                                  causal=True)
        ctx = ctx.transpose(0, 2, 1, 3).reshape(b, s, h_local * d)
        attn_out = RowParallelLinear(
            e, e, input_is_parallel=True, world_size=tp,
            params_dtype=cfg.param_dtype, quantize=qmode,
            quantize_group_size=qgs, name="out_proj")(ctx)
        x = x + attn_out.astype(x.dtype)

        h = FusedLayerNorm(e, eps=cfg.layernorm_eps, name="post_norm")(x)
        h = h.astype(dt)
        if self._is_moe_layer():
            from apex_tpu.transformer.moe import make_moe_mlp

            mlp_out, aux = make_moe_mlp(cfg, e, 4 * e, "gelu")(h)
            self.sow("intermediates", "moe_aux", aux.total)
        else:
            h = ColumnParallelLinear(
                e, 4 * e, gather_output=False, world_size=tp,
                params_dtype=cfg.param_dtype, quantize=qmode,
                quantize_group_size=qgs, name="mlp_in")(h)
            h = jax.nn.gelu(h, approximate=True)
            mlp_out = RowParallelLinear(
                4 * e, e, input_is_parallel=True, world_size=tp,
                params_dtype=cfg.param_dtype, quantize=qmode,
                quantize_group_size=qgs, name="mlp_out")(h)
        out = x + mlp_out.astype(x.dtype)
        return out if cache is None else (out, cache)


class GPTModel(nn.Module):
    """Decoder-only LM. ``__call__(input_ids)`` -> vocab-PARALLEL logits
    [B, S, vocab/tp] (feed to ``vocab_parallel_cross_entropy``); the LM head
    is tied to the vocab-parallel word embedding (Megatron tied embeddings,
    reference: standalone_gpt / parallel_state._EMBEDDING_GROUP)."""

    config: GPTConfig

    @nn.compact
    def __call__(self, input_ids, cache=None):
        cfg = self.config
        dt = resolve_compute_dtype(cfg.dtype)
        b, s = input_ids.shape
        if cfg.weight_quant() and cfg.num_experts > 0:
            raise NotImplementedError(
                "weight quantization (quantize_int8/weight_policy) does "
                "not cover MoE expert weights; the combination would "
                "silently serve fp experts")
        emb = VocabParallelEmbedding(
            cfg.vocab_size, cfg.hidden_size, world_size=cfg.tensor_parallel_size,
            params_dtype=cfg.param_dtype, name="word_embeddings")
        x = emb(input_ids)
        pos = self.param("position_embeddings", nn.initializers.normal(0.02),
                         (cfg.max_position_embeddings, cfg.hidden_size),
                         cfg.param_dtype)
        if cache is not None:
            # incremental decoding (models/generation.py): this chunk covers
            # absolute positions [len, len+s); caches hold K/V per layer and
            # the model returns (vocab-parallel logits, updated cache)
            if cfg.context_parallel:
                raise ValueError(
                    "incremental decoding does not compose with context "
                    "parallelism; decode on a dp/tp mesh instead")

            if is_paged(cache):
                # paged serving decode: an s-token block per SLOT, each
                # slot at its own absolute positions [len, len+s) —
                # gather per-slot position rows (the scheduler guards
                # the position cap; idle slots sit at 0)
                idx = jnp.clip(
                    cache["len"][:, None]
                    + jnp.arange(s, dtype=jnp.int32)[None, :],
                    0, cfg.max_position_embeddings - 1)      # (b, s)
                pos_s = jnp.take(pos, idx, axis=0)           # (b, s, e)
            else:
                t0 = check_chunk_bounds(cache, s,
                                        cfg.max_position_embeddings)
                pos_s = lax.dynamic_slice_in_dim(pos, t0, s)
        elif cfg.context_parallel and _axis_bound(CONTEXT_AXIS):
            # sequence sharded over ``context``: local chunk i covers global
            # positions [i*s, (i+1)*s) (or, zigzag, the two half-chunk
            # ranges i and 2cp-1-i)
            cp = lax.axis_size(CONTEXT_AXIS)
            if cp * s > cfg.max_position_embeddings:
                # dynamic_slice would CLAMP an out-of-range start and
                # silently reuse positions on late ranks
                raise ValueError(
                    f"global sequence cp*s = {cp}*{s} exceeds "
                    f"max_position_embeddings={cfg.max_position_embeddings}")
            i = lax.axis_index(CONTEXT_AXIS)
            if cfg.context_parallel_zigzag:
                if s % 2:
                    raise ValueError("zigzag CP needs an even local sequence")
                s_h = s // 2
                pos_s = jnp.concatenate([
                    lax.dynamic_slice_in_dim(pos, i * s_h, s_h),
                    lax.dynamic_slice_in_dim(
                        pos, (2 * cp - 1 - i) * s_h, s_h)])
            else:
                pos_s = lax.dynamic_slice_in_dim(pos, i * s, s)
        else:
            pos_s = pos[:s]
        # paged decode built a per-slot (b, 1, e) gather; the other paths
        # share one (s, e) row block broadcast over the batch
        x = (x + (pos_s if pos_s.ndim == 3 else pos_s[None, :, :])).astype(dt)
        # nn.remat (lifted jax.checkpoint): same param tree, same sown
        # intermediates, recompute-in-backward per block
        block_cls = nn.remat(ParallelDecoderBlock) if cfg.remat and cache is None \
            else ParallelDecoderBlock
        new_layers = []
        for i in range(cfg.num_layers):
            blk = block_cls(cfg, layer_idx=i, name=f"layer_{i}")
            if cache is None:
                x = blk(x)
            else:

                x, lc = blk(x, cache=layer_cache(cache, i))
                new_layers.append(lc)
        x = FusedLayerNorm(cfg.hidden_size, eps=cfg.layernorm_eps,
                           name="final_norm")(x)
        # tied LM head: local logits against the LOCAL vocab shard
        logits = emb.attend(x.astype(dt))
        if cache is None:
            return logits

        return logits, advance_cache(cache, new_layers, s)


def lm_token_loss(logits, labels, axis_name: str = MODEL_AXIS,
                  context_parallel: bool = False, extra=None):
    """Mean next-token loss from vocab-PARALLEL logits — the shared loss
    tail for the decoder LMs (GPT, Llama): vocab-parallel CE when the model
    axis is bound, log-softmax fallback otherwise, CP pmean of equal-size
    sequence chunks. ``extra`` (e.g. MoE aux losses computed on this rank's
    local tokens) is added BEFORE the CP pmean so per-rank terms combine to
    their global mean too."""
    if _axis_bound(axis_name):
        per_tok = vocab_parallel_cross_entropy(
            logits.astype(jnp.float32), labels, axis_name=axis_name)
    else:
        logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        per_tok = -jnp.take_along_axis(logp, labels[..., None], axis=-1)[..., 0]
    loss = per_tok.mean()
    if extra is not None:
        loss = loss + extra
    if context_parallel and _axis_bound(CONTEXT_AXIS):
        loss = lax.pmean(loss, CONTEXT_AXIS)
    return loss


def gpt_loss(model: GPTModel, variables, input_ids, labels,
             axis_name: str = MODEL_AXIS):
    """Mean next-token loss from vocab-parallel logits (+ MoE aux losses)."""
    moe_aux = jnp.zeros((), jnp.float32)
    if model.config.num_experts > 0:
        from apex_tpu.transformer.moe import collect_sown_aux

        logits, inter = model.apply(variables, input_ids,
                                    mutable=["intermediates"])
        moe_aux = collect_sown_aux(inter)
    else:
        logits = model.apply(variables, input_ids)
    return lm_token_loss(
        logits, labels, axis_name=axis_name,
        context_parallel=model.config.context_parallel, extra=moe_aux)
