"""Fused normalization modules (reference: apex/normalization/__init__.py)."""

from apex_tpu.normalization.fused_layer_norm import (  # noqa: F401
    FusedLayerNorm,
    FusedRMSNorm,
    MixedFusedLayerNorm,
    MixedFusedRMSNorm,
    fused_layer_norm,
    fused_layer_norm_affine,
    fused_rms_norm,
    fused_rms_norm_affine,
    mixed_dtype_fused_layer_norm_affine,
    mixed_dtype_fused_rms_norm_affine,
)
