"""FusedLayerNorm / FusedRMSNorm modules over the Pallas kernels.

Reference: apex/normalization/fused_layer_norm.py:~30-400 — nn.Modules
``FusedLayerNorm``/``FusedRMSNorm`` (+ ``MixedFused*`` variants that keep
params fp32 under fp16/bf16 inputs) and the functional entry points
``fused_layer_norm_affine`` etc. Here the modules are flax.linen Modules and
the functionals call the Pallas custom-vjp ops in apex_tpu/ops/layer_norm.py.
"""

from __future__ import annotations

import numbers
from typing import Sequence, Union

import flax.linen as nn
import jax
import jax.numpy as jnp

from apex_tpu.ops import layer_norm as _ln_op
from apex_tpu.ops import rms_norm as _rms_op

Shape = Union[int, Sequence[int]]


def _norm_size(normalized_shape: Shape) -> tuple:
    if isinstance(normalized_shape, numbers.Integral):
        return (int(normalized_shape),)
    return tuple(int(d) for d in normalized_shape)


def _flatten_norm_dims(x, normalized_shape):
    """Collapse the trailing normalized dims into one (the kernel normalizes
    the last dim; apex supports multi-dim normalized_shape)."""
    nd = len(normalized_shape)
    if tuple(x.shape[x.ndim - nd:]) != tuple(normalized_shape):
        raise ValueError(
            f"normalized_shape {tuple(normalized_shape)} does not match the "
            f"trailing dims of input shape {tuple(x.shape)}"
        )
    lead = x.shape[: x.ndim - nd]
    return x.reshape(lead + (-1,)), lead


def fused_layer_norm_affine(x, weight, bias, normalized_shape, eps=1e-6, memory_efficient=False):
    """Reference: apex/normalization/fused_layer_norm.py:fused_layer_norm_affine."""
    shape = _norm_size(normalized_shape)
    x2, lead = _flatten_norm_dims(x, shape)
    y = _ln_op(x2, weight.reshape(-1), bias.reshape(-1), eps, memory_efficient)
    return y.reshape(x.shape)


def fused_layer_norm(x, normalized_shape, eps=1e-6, memory_efficient=False):
    shape = _norm_size(normalized_shape)
    x2, _ = _flatten_norm_dims(x, shape)
    return _ln_op(x2, None, None, eps, memory_efficient).reshape(x.shape)


def fused_rms_norm_affine(x, weight, normalized_shape, eps=1e-6, memory_efficient=False):
    shape = _norm_size(normalized_shape)
    x2, _ = _flatten_norm_dims(x, shape)
    return _rms_op(x2, weight.reshape(-1), eps, memory_efficient).reshape(x.shape)


def fused_rms_norm(x, normalized_shape, eps=1e-6, memory_efficient=False):
    shape = _norm_size(normalized_shape)
    x2, _ = _flatten_norm_dims(x, shape)
    return _rms_op(x2, None, eps, memory_efficient).reshape(x.shape)


# "Mixed dtype" functionals: params stay fp32 while activations are 16-bit
# (reference: mixed_dtype_fused_layer_norm_affine / MixedFusedLayerNorm).
# The kernel always accumulates fp32, so these are the same entry points; the
# distinction survives in module param dtypes below.
mixed_dtype_fused_layer_norm_affine = fused_layer_norm_affine
mixed_dtype_fused_rms_norm_affine = fused_rms_norm_affine


class FusedLayerNorm(nn.Module):
    """Drop-in for apex.normalization.FusedLayerNorm (fused_layer_norm.py:~300).

    Args mirror the reference: ``normalized_shape``, ``eps``,
    ``elementwise_affine``, ``memory_efficient``.
    """

    normalized_shape: Shape
    eps: float = 1e-5
    elementwise_affine: bool = True
    memory_efficient: bool = False
    param_dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        shape = _norm_size(self.normalized_shape)
        if self.elementwise_affine:
            weight = self.param("weight", nn.initializers.ones, shape, self.param_dtype)
            bias = self.param("bias", nn.initializers.zeros, shape, self.param_dtype)
            return fused_layer_norm_affine(
                x, weight, bias, shape, self.eps, self.memory_efficient
            )
        return fused_layer_norm(x, shape, self.eps, self.memory_efficient)


class FusedRMSNorm(nn.Module):
    """Drop-in for apex.normalization.FusedRMSNorm."""

    normalized_shape: Shape
    eps: float = 1e-5
    elementwise_affine: bool = True
    memory_efficient: bool = False
    param_dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        shape = _norm_size(self.normalized_shape)
        if self.elementwise_affine:
            weight = self.param("weight", nn.initializers.ones, shape, self.param_dtype)
            return fused_rms_norm_affine(x, weight, shape, self.eps, self.memory_efficient)
        return fused_rms_norm(x, shape, self.eps, self.memory_efficient)


# The reference's Mixed* classes differ only in keeping fp32 params under
# 16-bit activations — which is already this module's default (param_dtype
# fp32), so they are aliases kept for API parity.
MixedFusedLayerNorm = FusedLayerNorm
MixedFusedRMSNorm = FusedRMSNorm
