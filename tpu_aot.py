"""Offline AOT-Mosaic evidence tier (VERDICT r4 next-round #1).

Compiles every Pallas kernel at the on-chip suite's exact shapes — plus the
full BERT-Large train step at the bench gate config and the flash-attention
autotune candidate set — against a **device-less TPU topology**
(``jax.experimental.topologies``). No tunnel, no chip: Mosaic block-rule
violations, illegal layouts, and HBM blowups (the r3 86 GB relayout class)
all surface at this compile/memory level.

Recipe (judge-verified on this box, offline):
  - ``get_topology_desc("v5e:2x4", platform="tpu")`` + ``make_mesh``
  - wrap the kernel in ``shard_map`` with fully-replicated ``P()`` specs
    (plain jit hits "Mosaic kernels cannot be automatically partitioned");
    every device then runs the FULL arrays, so ``memory_analysis()`` is the
    single-chip memory picture
  - ``APEX_TPU_FORCE_MOSAIC=1`` so ``ops._dispatch.interpret()`` picks the
    Mosaic path even though the default backend is CPU
  - assert ``tpu_custom_call`` present in the lowered text and
    argument+output+temp bytes under the v5e 16 GiB HBM budget

Writes ``AOT_<tag>.json`` and prints one summary JSON line. Runs standalone
(``python tpu_aot.py``) and is invoked by run_tpu_round.sh BEFORE the tunnel
probe so a dead-tunnel round still banks this artifact.
"""

from __future__ import annotations

import argparse
import functools
import json
import os
import re
import sys
import time
import traceback

os.environ["APEX_TPU_FORCE_MOSAIC"] = "1"
# the CI subset (tests/test_aot_mosaic.py) may run while this sweep or the
# tunnel watcher holds the libtpu lockfile — allow concurrent loads
os.environ.setdefault("ALLOW_MULTIPLE_LIBTPU_LOAD", "1")

REPO = os.path.dirname(os.path.abspath(__file__))
HBM_BUDGET = 16 * 1024 ** 3  # v5e HBM per chip

SEQ, HIDDEN, VOCAB = 512, 1024, 30528


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def _setup_jax():
    import jax

    # sitecustomize imports jax with JAX_PLATFORMS=axon; flip the default
    # backend to CPU so constant materialization never touches the (possibly
    # dead) tunnel. The TPU work here is all topology-AOT.
    jax.config.update("jax_platforms", "cpu")
    import bench

    bench._enable_compile_cache(jax)
    return jax


#: topology candidates, shared with tpu_profile.aot_overlap_check — keep
#: the list in ONE place so the sweep and the overlap check never disagree
TOPOLOGY_NAMES = ("v5e:2x4", "v5litepod-8", "v5e-8")


def _topology():
    from jax.experimental import topologies

    errs = []
    for name in TOPOLOGY_NAMES:
        try:
            topo = topologies.get_topology_desc(name, platform="tpu")
            return name, topo
        except Exception as e:  # noqa: BLE001
            errs.append(f"{name}: {type(e).__name__}: {str(e)[:80]}")
    raise RuntimeError("no TPU topology available: " + "; ".join(errs))


def _mesh(topo):
    from jax.experimental import topologies

    return topologies.make_mesh(topo, (8,), ("data",))


def compile_replicated(mesh, fn, arg_structs, donate=()):
    """shard_map(fn) with all-replicated specs, AOT-compiled for the topology.

    Returns the compiled executable (callers read the lowered text via
    ``compiled.as_text()``). Each device runs the full arrays, so
    per-device memory_analysis == the single-chip footprint.
    """
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    sm = jax.shard_map(fn, mesh=mesh, in_specs=P(), out_specs=P(),
                       check_vma=False)
    repl = NamedSharding(mesh, P())

    def stamp(s):
        return jax.ShapeDtypeStruct(s.shape, s.dtype, sharding=repl)

    args = jax.tree.map(stamp, tuple(arg_structs))
    compiled = jax.jit(sm, donate_argnums=donate).lower(*args).compile()
    return compiled


_DTYPE_BYTES = {"f64": 8, "f32": 4, "bf16": 2, "f16": 2, "s32": 4,
                "u32": 4, "s8": 1, "u8": 1, "pred": 1, "s16": 2, "u16": 2,
                "s64": 8, "u64": 8, "c64": 8, "c128": 16}


def hlo_red_flags(txt, threshold_bytes=256 * 1024 * 1024):
    """Static perf-lint over compiled HLO: copy/transpose ops whose RESULT
    exceeds ``threshold_bytes``. The r3 86 GB incident was exactly this
    class — a relayout intermediate far larger than any program tensor —
    and it is visible in compiled text before a chip ever runs. Returns a
    list of {op, bytes} (empty = clean).

    Scans only the ENTRY computation: ops inside fusion bodies never
    materialize their own buffers, so a big fused transpose is not a red
    flag (code-review r5)."""
    entry = txt.find("\nENTRY ")
    if entry >= 0:
        txt = txt[entry:]
    flags = []
    pat = re.compile(r"= (\w+)\[([\d,]*)\][^ ]* (copy|transpose)\(")
    for m in pat.finditer(txt):
        dt, dims, op = m.group(1), m.group(2), m.group(3)
        n = 1
        for d in dims.split(","):
            if d:
                n *= int(d)
        b = n * _DTYPE_BYTES.get(dt, 4)
        if b > threshold_bytes:
            flags.append({"op": op, "dtype": dt, "shape": dims, "bytes": b})
    flags.sort(key=lambda f: -f["bytes"])
    return flags[:8]


def case_result(mesh, fn, arg_structs, donate=()):
    import jax  # noqa: F401

    t0 = time.perf_counter()
    compiled = compile_replicated(mesh, fn, arg_structs, donate)
    dt = time.perf_counter() - t0
    txt = compiled.as_text()
    ma = compiled.memory_analysis()
    arg_b = int(ma.argument_size_in_bytes)
    out_b = int(ma.output_size_in_bytes)
    tmp_b = int(ma.temp_size_in_bytes)
    alias_b = int(getattr(ma, "alias_size_in_bytes", 0))
    # donated inputs alias outputs — don't double count them
    peak = arg_b + out_b + tmp_b - alias_b
    return {
        "ok": True,
        "tpu_custom_call_sites": txt.count("tpu_custom_call"),
        "argument_bytes": arg_b,
        "output_bytes": out_b,
        "temp_bytes": tmp_b,
        "alias_bytes": alias_b,
        "peak_estimate_bytes": peak,
        "peak_estimate_gib": round(peak / 1024 ** 3, 3),
        "under_16gib_budget": peak < HBM_BUDGET,
        "giant_copy_flags": hlo_red_flags(txt),
        "compile_s": round(dt, 1),
    }


# ---------------------------------------------------------------------------
# kernel cases — shapes mirror tests/test_real_tpu_kernels.py exactly
# ---------------------------------------------------------------------------

def _sds(shape, dtype):
    import jax

    return jax.ShapeDtypeStruct(shape, dtype)


def kernel_cases():
    """Yield (name, fn, arg_structs[, donate]) for every on-chip test config."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from apex_tpu.ops import (flash_attention, flash_attention_with_lse,
                              flat_buffer, optim_kernels,
                              softmax_cross_entropy)
    from apex_tpu.ops.group_norm import group_norm_nhwc
    from apex_tpu.ops.layer_norm import layer_norm
    from apex_tpu.ops.scaled_softmax import scaled_upper_triang_masked_softmax

    f32, bf16, i32 = jnp.float32, jnp.bfloat16, jnp.int32

    # -- test_layer_norm_fwd_bwd_bench_shapes
    ln = functools.partial(layer_norm, eps=1e-12)
    yield ("layer_norm_fwd", ln,
           [_sds((8 * SEQ, HIDDEN), f32), _sds((HIDDEN,), f32),
            _sds((HIDDEN,), f32)])
    yield ("layer_norm_bwd",
           jax.grad(lambda x, g, b: jnp.sum(ln(x, g, b) ** 2),
                    argnums=(0, 1, 2)),
           [_sds((8 * SEQ, HIDDEN), f32), _sds((HIDDEN,), f32),
            _sds((HIDDEN,), f32)])

    # -- test_flash_attention_fwd_bwd_seq512
    qkv = [_sds((2, 16, SEQ, 64), bf16)] * 3
    yield ("flash_fwd_seq512", flash_attention, qkv)
    yield ("flash_bwd_seq512",
           jax.grad(lambda q, k, v: jnp.sum(
               flash_attention(q, k, v).astype(f32) ** 2),
               argnums=(0, 1, 2)), qkv)

    # -- test_flash_attention_causal_and_dropout_compile
    q8 = _sds((2, 8, SEQ, 64), bf16)
    cd = functools.partial(flash_attention, causal=True, dropout_rate=0.1,
                           dropout_seed=7)
    yield ("flash_causal_dropout_fwd", lambda q: cd(q, q, q), [q8])
    yield ("flash_causal_dropout_bwd",
           jax.grad(lambda q: jnp.sum(cd(q, q, q).astype(f32))), [q8])

    # -- test_xentropy_vocab30528
    n = 2 * SEQ
    yield ("xentropy_fwd", softmax_cross_entropy,
           [_sds((n, VOCAB), f32), _sds((n,), i32)])
    yield ("xentropy_bwd",
           jax.grad(lambda l, y: softmax_cross_entropy(l, y).sum()),
           [_sds((n, VOCAB), f32), _sds((n,), i32)])

    # -- test_scaled_masked_softmax_seq512
    yield ("scaled_upper_triang_softmax",
           functools.partial(scaled_upper_triang_masked_softmax, scale=0.125),
           [_sds((64, SEQ, SEQ), bf16)])

    # -- test_fused_optimizer_kernels_bert_large_size
    opt_shapes = {"emb": (VOCAB, 64), "w1": (HIDDEN, HIDDEN),
                  "w2": (4 * HIDDEN, HIDDEN), "b": (HIDDEN,)}
    opt_tree = {k: _sds(s, f32) for k, s in opt_shapes.items()}
    spec = flat_buffer.build_spec(opt_tree)
    seg = np.asarray(spec.segment_rows())
    buf = _sds((spec.total_rows, flat_buffer.LANE), f32)
    yield ("optim_adam_bert_large_buffer",
           functools.partial(optim_kernels.adam_update, beta1=0.9, beta2=0.999,
                             eps=1e-8, weight_decay=0.01, lr=1e-3, step=1),
           [buf] * 4, (1, 2, 3))
    yield ("optim_lamb_bert_large_buffer",
           lambda g, p, m, v: optim_kernels.lamb_update(
               g, p, m, v, jnp.asarray(seg), spec.num_tensors, beta1=0.9,
               beta2=0.999, eps=1e-6, weight_decay=0.01, lr=1e-3, step=1),
           [buf] * 4, (1, 2, 3))
    # LAMB at more shapes (ADVICE r5): its phase-1 kernel holds 7 big
    # (blk, LANE) buffers — the Adam-class scoped-VMEM risk — so sweep a
    # GPT-2-small-sized buffer and an odd-row tail, not just BERT-Large
    for lamb_tag, lamb_tree in (
            ("gpt2s", {"emb": (50304, 16), "w1": (768, 768),
                       "w2": (3072, 768), "b": (768,)}),
            ("odd_tail", {"w": (1000, 1001), "b": (7,)}),
    ):
        lspec = flat_buffer.build_spec(
            {k: _sds(s, f32) for k, s in lamb_tree.items()})
        lseg = np.asarray(lspec.segment_rows())
        lbuf = _sds((lspec.total_rows, flat_buffer.LANE), f32)
        yield (f"optim_lamb_{lamb_tag}_buffer",
               lambda g, p, m, v, lseg=lseg, lspec=lspec:
               optim_kernels.lamb_update(
                   g, p, m, v, jnp.asarray(lseg), lspec.num_tensors,
                   beta1=0.9, beta2=0.999, eps=1e-6, weight_decay=0.01,
                   lr=1e-3, step=1),
               [lbuf] * 4, (1, 2, 3))
    yield ("optim_global_grad_norm",
           lambda g: optim_kernels.global_grad_norm_and_finite(
               g, jnp.asarray(seg), spec.num_tensors),
           [buf])

    # -- test_group_norm_kernel_path / _backward_kernel_path
    # custom_vjp nondiff_argnums must stay positional
    gn = lambda x, w, b: group_norm_nhwc(x, w, b, 4, 1e-5, "silu")  # noqa: E731
    yield ("group_norm_fwd_bf16", gn,
           [_sds((4, 16, 16, 512), bf16), _sds((512,), f32),
            _sds((512,), f32)])
    yield ("group_norm_bwd_fp32",
           jax.grad(lambda x, w, b: jnp.sum(gn(x, w, b) ** 2),
                    argnums=(0, 1, 2)),
           [_sds((2, 16, 16, 512), f32), _sds((512,), f32),
            _sds((512,), f32)])

    # -- test_flash_attention_with_lse_on_chip
    yield ("flash_lse_fwd", flash_attention_with_lse,
           [q8, q8, q8])
    yield ("flash_lse_bwd_with_lse_cotangent",
           jax.grad(lambda q, k, v: (
               lambda o_lse: jnp.sum(o_lse[1]) +
               jnp.sum(o_lse[0].astype(f32)))(
               flash_attention_with_lse(q, k, v))),
           [q8, q8, q8])

    # -- test_flash_attention_sliding_window
    yield ("flash_window_wide_fwd",
           lambda q: flash_attention(q, q, q, causal=True, window=SEQ), [q8])
    yield ("flash_window128_bwd",
           jax.grad(lambda q: jnp.sum(flash_attention(
               q, q, q, causal=True, window=128).astype(f32) ** 2)), [q8])

    # -- paged-attention serving decode kernel (apex_tpu/serving): GPT-2
    # small pool at 8 slots — 512 usable pages of 16 tokens (+ null page),
    # 32-page tables (512-token sequences). Scalar-prefetch block tables
    # are the new Mosaic feature this case gates.
    from apex_tpu.ops.paged_attention import paged_attention

    yield ("paged_attention_gpt2s_decode", paged_attention,
           [_sds((8, 12, 1, 64), bf16), _sds((513, 12, 16, 64), bf16),
            _sds((513, 12, 16, 64), bf16), _sds((8, 32), i32),
            _sds((8,), i32)])

    # -- the s>1 query-block generalization (ISSUE 13): the speculative
    # verify step reads a 4-token block (draft_len 3 + 1 pending) per
    # slot through the SAME kernel — the per-row causal band
    # (len - s + i) is the only new Mosaic surface, so one s=4 case
    # gates it at the gpt2s pool shape.
    yield ("gpt2s_paged_spec_verify", paged_attention,
           [_sds((8, 12, 4, 64), bf16), _sds((513, 12, 16, 64), bf16),
            _sds((513, 12, 16, 64), bf16), _sds((8, 32), i32),
            _sds((8,), i32)])

    # -- quantized KV pages (docs/serving.md "Quantized KV pages"): the
    # SAME decode step over an int8 pool with per-(page, kv_head) f32
    # scales dequantized inside the kernel. The new Mosaic surfaces this
    # case gates: int8 page tiles at the (1, 1, page, d) block shape and
    # the (1, 1) scale blocks indexed through the prefetched table.
    yield ("gpt2s_paged_decode_int8kv",
           lambda q, k, v, bt, ln, ks, vs: paged_attention(
               q, k, v, bt, ln, k_scales=ks, v_scales=vs),
           [_sds((8, 12, 1, 64), bf16), _sds((513, 12, 16, 64), jnp.int8),
            _sds((513, 12, 16, 64), jnp.int8), _sds((8, 32), i32),
            _sds((8,), i32), _sds((513, 12), f32), _sds((513, 12), f32)])

    # -- serving path (r5): tpu_decode_bench.py's exact programs — flash
    # prefill + lax.scan single-token decode + argmax, GPT-2 small at the
    # bench config (batch 8, prompt 128, 128 new tokens, bf16), fp AND
    # int8 W8A8. The decode path had only ever compiled on CPU.
    import dataclasses

    from apex_tpu.models.generation import generate
    from apex_tpu.models.gpt import GPTModel, gpt2_small_config

    dcfg = gpt2_small_config(dtype=bf16)
    dmodel = GPTModel(dcfg)
    prompt_s = _sds((8, 128), i32)
    dvars = jax.eval_shape(
        lambda: dmodel.init(jax.random.PRNGKey(0), jnp.zeros((8, 8), i32)))

    def decode_fp(variables, prompt):
        return generate(dmodel, variables, prompt, max_new_tokens=128,
                        max_len=256, axis_name="unbound")

    yield ("gpt2_small_decode128_fp", decode_fp, [dvars, prompt_s])

    qmodel = GPTModel(dataclasses.replace(dcfg, quantize_int8=True))
    qvars = jax.eval_shape(
        lambda: qmodel.init(jax.random.PRNGKey(0), jnp.zeros((8, 8), i32)))

    def decode_int8(variables, prompt):
        return generate(qmodel, variables, prompt, max_new_tokens=128,
                        max_len=256, axis_name="unbound")

    yield ("gpt2_small_decode128_int8", decode_int8, [qvars, prompt_s])

    # -- prefix-cached serving admission (apex_tpu/serving/prefix_cache):
    # the shared-prefix admission program at GPT-2 small shapes — gather 8
    # cached pages (128 shared-header tokens) from the pool into the
    # contiguous buffer, run the 128-token tail forward against it (dense
    # cached attention + the Pallas layer-norm kernels), pop private
    # pages with refcount bookkeeping, scatter the tail K/V. This is the
    # one program prefix caching adds to the serving path; the decode
    # step itself is the (already-swept) paged program.
    from apex_tpu.serving import kv_pool as _kv_pool
    from apex_tpu.serving.scheduler import make_shared_admit

    pcache_abs = jax.eval_shape(
        lambda: _kv_pool.init_paged_cache(dcfg, 8, num_pages=513,
                                          page_size=16))
    pc_max_pages = pcache_abs["block_tables"].shape[1]
    prefix_admit = make_shared_admit(dmodel, t_start=128, tail_bucket=128,
                                     axis_name="unbound")

    yield ("gpt2s_prefix_cached_admit", prefix_admit,
           [pcache_abs, dvars, _sds((1, 128), i32), _sds((), i32),
            _sds((), i32), _sds((pc_max_pages,), i32), _sds((), i32),
            _sds((2,), jnp.uint32)])

    # -- chunked-prefill step (ISSUE 13): one 16-token prompt chunk of
    # one slot rides the paged s>1 path straight into the slot's pages
    # (no contiguous staging, no scatter) — the program the frontend
    # interleaves between decode chunks to bound TTFT.
    from apex_tpu.serving.scheduler import make_prefill_chunk

    chunk_step = make_prefill_chunk(dmodel, chunk=16, axis_name="unbound")

    yield ("gpt2s_chunked_prefill_step", chunk_step,
           [pcache_abs, dvars, _sds((1, 16), i32), _sds((), i32),
            _sds((), i32), _sds((2,), jnp.uint32), _sds((), i32)])

    # -- quantized weight streaming (docs/serving.md "Quantized weight
    # streaming"): the paged decode chunk over a gpt2-small built with
    # the int8 WeightPrecisionPolicy — every block linear stages the
    # fused dequant-matmul kernel (int8 weight + f32 scale operands,
    # dequant in VMEM next to the contraction) alongside the paged
    # attention gather. The new Mosaic surfaces: int8 weight tiles at
    # (block_out, in) and the degenerate (1, block_out) scale blocks.
    from apex_tpu.ops.quant import WeightPrecisionPolicy
    from apex_tpu.serving.scheduler import PagedDecodeEngine

    wmodel = GPTModel(dataclasses.replace(
        dcfg, weight_policy=WeightPrecisionPolicy("int8")))
    wengine = PagedDecodeEngine(wmodel, variables=None, num_slots=8,
                                page_size=16, num_pages=513,
                                max_pages_per_seq=32, sync_every=4)
    wcache_abs = jax.tree.map(lambda x: _sds(x.shape, x.dtype),
                              wengine.cache)
    wvars = jax.eval_shape(
        lambda: wmodel.init(jax.random.PRNGKey(0), jnp.zeros((8, 8), i32)))

    yield ("gpt2s_paged_decode_w8", wengine._step_fn(),
           [wcache_abs, wvars, _sds((8,), i32), _sds((8,), jnp.bool_),
            _sds((8,), i32), _sds((8, 2), jnp.uint32), _sds((8,), i32)])

    # -- the int4 half of the same kernel, raw, at the gpt2s block-linear
    # shape: packed nibbles (out, in/2) uint8 + per-(group, out) f32
    # scales — gates the nibble-extract widening and the sub-sublane
    # (n_groups, block_out) scale block under Mosaic's tiling rules.
    from apex_tpu.ops.quant import fused_dequant_matmul

    yield ("gpt2s_fused_dequant_w4", fused_dequant_matmul,
           [_sds((8, 768), bf16), _sds((768, 384), jnp.uint8),
            _sds((6, 768), f32)])

    # -- tiered KV pool (ISSUE 17): the demote-side page gather (pure
    # read — cache NOT donated) and the promote-side scatter (cache
    # donated, pops the free stack like an allocation). Both are plain
    # XLA data movers by design — no Mosaic kernel, a fixed null-padded
    # HOST_COPY_CHUNK page row, depth as a traced scalar — so the pin
    # is the inverse of the others: zero tpu_custom_call sites, and no
    # giant-copy flags (a relayout sneaking into the copy path would be
    # pure overhead on the host-link DMA).
    chunk_row = _sds((_kv_pool.HOST_COPY_CHUNK,), i32)
    tiles_abs = jax.eval_shape(_kv_pool.gather_pages, pcache_abs,
                               chunk_row)

    yield ("gpt2s_host_tier_gather", _kv_pool.gather_pages,
           [pcache_abs, chunk_row])

    yield ("gpt2s_host_tier_promote", _kv_pool.promote_pages,
           [pcache_abs, chunk_row, _sds((), i32), tiles_abs], (0,))


def tight_headdim_cases():
    """The compile half of the tight-head-dim gate (VERDICT r4 next #3):
    module flag set, d=64 stays unpadded instead of zero-padding to 128."""
    import importlib

    import jax
    import jax.numpy as jnp

    fa_impl = importlib.import_module("apex_tpu.ops.flash_attention")
    flash_attention = fa_impl.flash_attention
    q8 = _sds((2, 8, SEQ, 64), jnp.bfloat16)
    qkv16 = [_sds((2, 16, SEQ, 64), jnp.bfloat16)] * 3

    cases = [
        ("flash_tight_headdim_fwd",
         functools.partial(flash_attention, causal=True), [q8, q8, q8]),
        ("flash_tight_headdim_bwd",
         jax.grad(lambda q: jnp.sum(flash_attention(
             q, q, q, causal=True).astype(jnp.float32) ** 2)), [q8]),
        ("flash_tight_headdim_bench_shape_bwd",
         jax.grad(lambda q, k, v: jnp.sum(flash_attention(
             q, k, v, causal=True).astype(jnp.float32) ** 2),
             argnums=(0, 1, 2)), qkv16),
    ]
    return fa_impl, cases


def moe_case():
    import jax
    import jax.numpy as jnp

    from apex_tpu.transformer.moe import MoEMLP

    d, ff, e, k, t = 1024, 4096, 8, 2, 2048
    layer = MoEMLP(hidden_size=d, ffn_hidden_size=ff, num_experts=e, k=k,
                   capacity_factor=1.25, expert_world_size=1,
                   axis_name="nope")
    x_s = _sds((t, d), jnp.bfloat16)
    abs_vars = jax.eval_shape(
        lambda: layer.init(jax.random.PRNGKey(0),
                           jnp.zeros((t, d), jnp.bfloat16)))
    params_abs = abs_vars["params"]

    def loss_and_grad(p, xx):
        def f(pp):
            y, aux = layer.apply({"params": pp}, xx)
            return jnp.sum(y.astype(jnp.float32) ** 2) + aux.total
        return jax.value_and_grad(f)(p)

    return ("moe_dense_dispatch_grad", loss_and_grad, [params_abs, x_s])


def bert_train_step_case(batch_per_chip=8, remat=False):
    """The full bench-gate program: BERT-Large loss+grads+FusedLAMB update at
    batch ``batch_per_chip``, seq 512 — all kernels in one compiled program.
    Params/optimizer state are abstract (eval_shape + a field-initialized
    FusedLAMB), so no 1.4 GB host arrays are materialized."""
    import dataclasses

    import jax
    import jax.numpy as jnp
    import numpy as np

    from apex_tpu.models import (BertForPreTraining, bert_large_config,
                                 make_pretrain_step, synthetic_batch)
    from apex_tpu.ops import flat_buffer
    from apex_tpu.optimizers import FusedLAMB
    from apex_tpu.optimizers.common import path_name

    cfg = bert_large_config()
    if remat:
        cfg = dataclasses.replace(cfg, remat=True)
    model = BertForPreTraining(cfg)
    rng = np.random.default_rng(0)
    batch = synthetic_batch(rng, cfg, batch_per_chip, SEQ)
    abs_params = jax.eval_shape(
        lambda: model.init(jax.random.PRNGKey(0), batch["input_ids"],
                           batch["token_type_ids"],
                           batch["attention_mask"])["params"])
    spec = flat_buffer.build_spec(abs_params)
    seg_rows = spec.segment_rows()

    # field-initialize the optimizer facade (the ctor would materialize the
    # master/state buffers; only spec/seg_rows/defaults matter for tracing)
    opt = object.__new__(FusedLAMB)
    opt.defaults = dict(lr=1e-4, beta1=0.9, beta2=0.999, eps=1e-6,
                        weight_decay=0.01, max_grad_norm=1.0)
    opt.spec = spec
    opt.seg_rows = seg_rows
    opt.bias_correction = True
    opt.grad_averaging = True
    opt.use_nvlamb = False
    exclude = lambda n: "bias" in n or "norm" in n.lower()  # noqa: E731
    paths, _ = jax.tree_util.tree_flatten_with_path(abs_params)
    opt.wd_per_segment = np.asarray(
        [0.0 if exclude(path_name(p)) else 0.01 for p, _ in paths],
        np.float32)

    step_fn = make_pretrain_step(model)
    hyper = {k: jnp.float32(v) for k, v in opt.defaults.items()}

    def train_step(params, master, m, v, stepc, batch, i):
        loss, grads = step_fn(params, batch, i)
        g_flat = flat_buffer.flatten(grads, spec)
        new_step = stepc + 1
        new_master, new_state = opt._update(
            g_flat, master, {"m": m, "v": v}, new_step,
            dict(hyper, grad_scale=jnp.float32(1.0), noop=jnp.float32(0.0),
                 wd_per_segment=jnp.asarray(opt.wd_per_segment)))
        params_out = flat_buffer.unflatten(new_master, spec)
        return loss, params_out, new_master, new_state["m"], new_state["v"], new_step

    buf = _sds((spec.total_rows, flat_buffer.LANE), jnp.float32)
    batch_s = {k: _sds(tuple(np.shape(val)), jnp.asarray(val).dtype)
               for k, val in batch.items()}
    args = [abs_params, buf, buf, buf, _sds((), jnp.int32), batch_s,
            _sds((), jnp.int32)]
    name = f"bert_large_train_step_b{batch_per_chip}" + (
        "_remat" if remat else "")
    # donate master/m/v — mirrors FusedOptimizerBase's donate_argnums=(1, 2)
    return (name, train_step, args, (1, 2, 3))


# ---------------------------------------------------------------------------
# multi-chip sharded programs (r5): the dryrun cases only ever RUN on the
# virtual CPU mesh in interpret mode — here the same sharded programs
# (ring-attention CP, zigzag CP + window, Megatron TP, T5 TP + cached
# decode, MoE EP x expert-TP, 1F1B pipeline) are Mosaic-compiled for the
# real v5e topology, proving the multi-chip path compiles for TPU hardware
# ---------------------------------------------------------------------------

import contextlib


@contextlib.contextmanager
def _host_interpret():
    """Temporarily drop FORCE_MOSAIC for code that EXECUTES on the CPU host
    (e.g. building real param trees) — Mosaic lowering is compile-only."""
    prior = os.environ.get("APEX_TPU_FORCE_MOSAIC")
    os.environ["APEX_TPU_FORCE_MOSAIC"] = "0"
    try:
        yield
    finally:
        if prior is None:
            os.environ.pop("APEX_TPU_FORCE_MOSAIC", None)
        else:
            os.environ["APEX_TPU_FORCE_MOSAIC"] = prior


def _topo_mesh(topo, shape, names=("data", "stage", "context", "model")):
    import numpy as np
    from jax.sharding import Mesh

    n = 1
    for s in shape:
        n *= s
    return Mesh(np.asarray(topo.devices[:n]).reshape(shape), names)


MULTICHIP_CASE_NAMES = (
    "cp2_ring_attention_grad",
    "cp2_zigzag_window_grad",
    "tp2_megatron_gpt_grad",
    "tp2_t5_grad_and_cached_decode",
    "ep2_etp2_moe_grad",
    "pp2_tp2_1f1b_pipeline_step",
    "tp4_paged_engine_admit",
    "tp4_paged_engine_decode_chunk",
    "tp4_paged_engine_decode_w8",
)

#: the tensor-parallel serving acceptance shape (docs/tp_serving.md):
#: 384 slots x 32 pages of a GPT at hidden 1024 / 8 heads — head_dim
#: 128, so page tiles are (32, 128): lane-exact, NO tiled-layout
#: padding, and the unpadded byte accounting below IS the physical HBM
#: footprint. 12289 pages x 1.5 MiB = 18.0 GiB UNSHARDED — over one
#: v5e chip's 16 GiB — sharded tp=4 over the v5e:2x4 topology (4.5 GiB
#: head shard per chip) the admit+decode programs compile under the
#: per-chip budget. tests/test_aot_mosaic.py asserts both halves of
#: that inequality. Two shape lessons are baked in here (both found by
#: this case's own compile failures): (a) GPT-2 small's head_dim 64
#: pads 2x in TPU tiled layout — the first 512-slot d=64 attempt OOM'd
#: at 25.6 GiB from padding alone; lane-align the head dim; (b) the
#: decode chunk's lax.scan DOUBLE-BUFFERS the pool carry in XLA, so a
#: chip needs ~2x its pool shard transient — which is why 18 GiB
#: shards over four chips, not two (2 x 9 GiB + weights > 16 GiB).
#: Both lessons are now lint rules (mem-padding-blowup and
#: mem-scan-carry-double-buffer, `python -m apex_tpu.analysis --mem`):
#: the next pool that repeats either mistake dies in the CPU-only mem
#: gate, and tests/test_aot_mosaic.py pins the lint tier's static
#: per-chip peaks within +/-20% of this sweep's memory_analysis() so
#: the two accountings cannot silently drift apart.
TP_SERVING_SLOTS = 384
TP_SERVING_PAGE_SIZE = 32
TP_SERVING_MAX_PAGES_PER_SEQ = 32
TP_SERVING_TP = 4


def tp_serving_config(weight_policy=None):
    """The acceptance model: GPT-2-small depth at hidden 1024 / 8 heads
    (head_dim 128 — lane-exact page tiles), tp=4, bf16. Pass
    ``weight_policy="int8"`` for the quantized-weight-streaming variant
    (every block linear narrow + scale, fused in-kernel dequant)."""
    import jax.numpy as jnp

    from apex_tpu.models.gpt import gpt2_small_config

    pol = None
    if weight_policy is not None:
        from apex_tpu.ops.quant import WeightPrecisionPolicy
        pol = WeightPrecisionPolicy(weight_policy)
    return gpt2_small_config(hidden_size=1024, num_heads=8,
                             dtype=jnp.bfloat16,
                             tensor_parallel_size=TP_SERVING_TP,
                             weight_policy=pol)


def tp_serving_pool_bytes() -> int:
    """The UNSHARDED pool's bytes at the TP acceptance shape (what a
    single chip would have to hold)."""
    cfg = tp_serving_config()
    num_pages = 1 + TP_SERVING_SLOTS * TP_SERVING_MAX_PAGES_PER_SEQ
    kv_heads = getattr(cfg, "num_kv_heads", cfg.num_heads)
    # k + v, bf16
    return (num_pages * cfg.num_layers * 2 * kv_heads
            * TP_SERVING_PAGE_SIZE * cfg.head_dim * 2)


def multichip_cases(topo):
    """Yield (name, build) mirroring __graft_entry__'s dryrun cases (same
    tiny shapes). ``build()`` is LAZY — it constructs (mesh, fn,
    arg_structs) only when called, so filtered-out cases cost nothing and a
    broken case can't abort the others (code-review r5)."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import PartitionSpec as P

    from apex_tpu.mesh import CONTEXT_AXIS, DATA_AXIS, MODEL_AXIS, STAGE_AXIS

    i32 = jnp.int32
    seq_sh = P(None, CONTEXT_AXIS)

    def build_cp_ring():
        from apex_tpu.models.gpt import GPTModel, gpt_loss, gpt_tiny_config

        mesh = _topo_mesh(topo, (4, 1, 2, 1))
        model = GPTModel(gpt_tiny_config(context_parallel=True))
        ids_s = _sds((2, 32), i32)
        params = jax.eval_shape(
            lambda: model.init(jax.random.PRNGKey(0),
                               jnp.zeros((2, 32), i32))["params"])

        def cp_grad(p, ii, ll):
            body = jax.shard_map(
                lambda pp_, i_, l_: gpt_loss(model, {"params": pp_}, i_, l_),
                mesh=mesh, in_specs=(P(), seq_sh, seq_sh), out_specs=P(),
                check_vma=False)
            return jax.value_and_grad(lambda q: body(q, ii, ll))(p)

        return mesh, cp_grad, [params, ids_s, ids_s]

    def build_cp_zigzag():
        from apex_tpu.models.llama import (LlamaModel, llama_loss,
                                           llama_tiny_config)

        mesh = _topo_mesh(topo, (4, 1, 2, 1))
        model = LlamaModel(llama_tiny_config(
            context_parallel=True, context_parallel_zigzag=True,
            sliding_window=12))
        ids_s = _sds((2, 32), i32)
        params = jax.eval_shape(
            lambda: model.init(jax.random.PRNGKey(0),
                               jnp.zeros((2, 32), i32))["params"])

        def zigzag_grad(p, ii, ll):
            body = jax.shard_map(
                lambda pp_, i_, l_: llama_loss(model, {"params": pp_},
                                               i_, l_),
                mesh=mesh, in_specs=(P(), seq_sh, seq_sh), out_specs=P(),
                check_vma=False)
            return jax.value_and_grad(lambda q: body(q, ii, ll))(p)

        return mesh, zigzag_grad, [params, ids_s, ids_s]

    def build_tp_megatron():
        from apex_tpu.models.gpt import GPTModel, gpt_loss, gpt_tiny_config

        mesh = _topo_mesh(topo, (4, 1, 1, 2))
        model = GPTModel(gpt_tiny_config(tensor_parallel_size=2))

        def tp_step(ii, ll):
            def body(i_, l_):
                v = model.init(jax.random.PRNGKey(0), i_)
                loss, _ = jax.value_and_grad(
                    lambda p: gpt_loss(model, {"params": p}, i_, l_))(
                    v["params"])
                return loss.reshape(1)
            return jax.shard_map(body, mesh=mesh, in_specs=(P(), P()),
                                 out_specs=P(MODEL_AXIS),
                                 check_vma=False)(ii, ll)

        ids16 = _sds((2, 16), i32)
        return mesh, tp_step, [ids16, ids16]

    def build_tp_t5():
        from apex_tpu.models.t5 import (T5Model, t5_generate, t5_loss,
                                        t5_tiny_config)

        mesh = _topo_mesh(topo, (4, 1, 1, 2))
        model = T5Model(t5_tiny_config(tensor_parallel_size=2))

        def t5_step(ei, di, ll):
            def body(e_, d_, l_):
                v = model.init(jax.random.PRNGKey(0), e_, d_)
                loss, _ = jax.value_and_grad(lambda p: t5_loss(
                    model, {"params": p}, e_, d_, l_))(v["params"])
                toks = t5_generate(model, v, e_, 3)
                return loss.reshape(1), toks
            return jax.shard_map(body, mesh=mesh, in_specs=(P(), P(), P()),
                                 out_specs=(P(MODEL_AXIS), P()),
                                 check_vma=False)(ei, di, ll)

        return mesh, t5_step, [_sds((2, 12), i32), _sds((2, 8), i32),
                               _sds((2, 8), i32)]

    def build_moe():
        from apex_tpu.transformer.moe import MoEMLP

        mesh = _topo_mesh(topo, (2, 1, 1, 2))
        d, ff, e, k, t_per = 16, 32, 4, 2, 8
        layer = MoEMLP(hidden_size=d, ffn_hidden_size=ff, num_experts=e,
                       k=k, capacity_factor=float(e) / k + 1.0,
                       activation="swiglu", expert_world_size=2,
                       axis_name=DATA_AXIS, tensor_world_size=2,
                       tensor_parallel_axis="model")

        def moe_step(xx):
            def body(x_):
                v = layer.init(jax.random.PRNGKey(0), x_)

                def loss_fn(p):
                    y, aux = layer.apply({"params": p}, x_)
                    return jnp.mean(y * y) + aux.total

                loss, g = jax.value_and_grad(loss_fn)(v["params"])
                gnorm = sum(jnp.sum(l * l)
                            for l in jax.tree_util.tree_leaves(g))
                loss = jax.lax.pmean(jax.lax.pmean(loss, DATA_AXIS), "model")
                gnorm = jax.lax.psum(jax.lax.psum(gnorm, DATA_AXIS), "model")
                return loss, gnorm
            return jax.shard_map(body, mesh=mesh, in_specs=P(DATA_AXIS),
                                 out_specs=P(), check_vma=False)(xx)

        return mesh, moe_step, [_sds((t_per * 2, d), jnp.float32)]

    def build_pipeline():
        import __graft_entry__ as ge
        from apex_tpu.models.gpt_pipeline import make_gpt_pipeline_fns
        from apex_tpu.transformer.pipeline_parallel import (
            forward_backward_pipelining_without_interleaving as fwd_bwd)

        mesh = _topo_mesh(topo, (2, 2, 1, 2))
        with _host_interpret():   # builds REAL param trees on the CPU host
            cfg, mbs, labels, stacked = ge._build_stacked_gpt_pipeline(
                2, 2, m=4, b=2, s=16)
        first_fn, stage_fn, loss_fn = make_gpt_pipeline_fns(cfg)

        def pipe_step(p_stacked, mb, lb):
            def body(ps, m_, l_):
                local = jax.tree.map(lambda t: t[0, 0], ps)
                loss, grads = fwd_bwd(stage_fn, loss_fn, local, m_,
                                      loss_aux=l_, first_fn=first_fn,
                                      loss_with_params=True)
                new_p = jax.tree.map(lambda pi, gi: pi - 0.1 * gi,
                                     local, grads)
                return loss.reshape(1), jax.tree.map(
                    lambda t: t[None, None], new_p)
            return jax.shard_map(
                body, mesh=mesh,
                in_specs=(P(STAGE_AXIS, MODEL_AXIS), P(), P()),
                out_specs=(P(STAGE_AXIS), P(STAGE_AXIS, MODEL_AXIS)),
                check_vma=False)(p_stacked, mb, lb)

        stacked_s = jax.tree.map(
            lambda a: _sds(np.shape(a), jnp.asarray(a).dtype), stacked)
        return mesh, pipe_step, [stacked_s, _sds(mbs.shape, i32),
                                 _sds(labels.shape, i32)]

    def _build_tp_serving(kind, weight_policy=None):
        # the tensor-parallel PAGED SERVING programs (serving/tp.py):
        # the tp=TP_SERVING_TP engine's shard_map admission + decode
        # chunk with the pool's kv-head axis REALLY sharded over the
        # topology mesh — per-chip memory_analysis then proves a pool
        # one chip cannot hold (tp_serving_pool_bytes() > 16 GiB)
        # compiles under the per-chip budget when sharded
        from jax.sharding import Mesh, NamedSharding

        from apex_tpu.models.gpt import GPTModel
        from apex_tpu.serving.scheduler import prompt_bucket
        from apex_tpu.serving.tp import (TensorParallelPagedEngine,
                                         infer_variable_specs)

        mesh = Mesh(np.asarray(topo.devices[:TP_SERVING_TP]),
                    (MODEL_AXIS,))
        cfg = tp_serving_config(weight_policy=weight_policy)
        model = GPTModel(cfg)
        engine = TensorParallelPagedEngine(
            model, variables=None, mesh=mesh, abstract=True,
            num_slots=TP_SERVING_SLOTS,
            page_size=TP_SERVING_PAGE_SIZE,
            max_pages_per_seq=TP_SERVING_MAX_PAGES_PER_SEQ,
            sync_every=4)
        dvars_abs, var_specs = infer_variable_specs(model)
        dvars = jax.tree.map(
            lambda s, sp: jax.ShapeDtypeStruct(
                s.shape, s.dtype, sharding=NamedSharding(mesh, sp)),
            dvars_abs, var_specs)
        repl = NamedSharding(mesh, P())

        def rsds(shape, dtype):
            return jax.ShapeDtypeStruct(shape, dtype, sharding=repl)

        n = TP_SERVING_SLOTS
        # donate the cache (arg 0): in production the pool updates in
        # place; without it the in/out pool shards double-count and no
        # 16 GiB chip could ever hold a >8 GiB-sharded program
        if kind == "decode":
            args = [engine.cache, dvars, rsds((n,), i32),
                    rsds((n,), jnp.bool_), rsds((n,), i32),
                    rsds((n, 2), jnp.uint32), rsds((n,), i32)]
            return mesh, engine._step_fn(), args, (0,)
        bucket = prompt_bucket(128, TP_SERVING_PAGE_SIZE,
                               cfg.max_position_embeddings)
        args = [engine.cache, dvars, rsds((1, bucket), i32), rsds((), i32),
                rsds((), i32), rsds((), i32), rsds((2,), jnp.uint32),
                rsds((), i32)]
        return mesh, engine._admit_fn(bucket), args, (0,)

    def build_tp_paged_admit():
        return _build_tp_serving("admit")

    def build_tp_paged_decode():
        return _build_tp_serving("decode")

    def build_tp_paged_decode_w8():
        # the quantized-weight variant of the decode chunk: same sharded
        # pool, but every block linear's weight rides int8 (+ f32 scale)
        # through the fused dequant-matmul kernel — the per-chip peak
        # bytes must DROP vs the bf16 case (tests/test_aot_mosaic.py
        # asserts the inequality)
        return _build_tp_serving("decode", weight_policy="int8")

    builders = (build_cp_ring, build_cp_zigzag, build_tp_megatron,
                build_tp_t5, build_moe, build_pipeline,
                build_tp_paged_admit, build_tp_paged_decode,
                build_tp_paged_decode_w8)
    for name, build in zip(MULTICHIP_CASE_NAMES, builders):
        yield name, build


def multichip_aot(topo, only=None):
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    out = {}
    for name, build in multichip_cases(topo):
        if only and name not in only:
            continue
        log(f"multichip case {name}...")
        try:
            t0 = time.perf_counter()
            built = build()               # lazy: inside the per-case try
            mesh, fn, structs = built[:3]
            donate = built[3] if len(built) > 3 else ()
            repl = NamedSharding(mesh, P())
            # a builder may pre-stamp per-arg shardings (the TP serving
            # cases shard the pool's head axis); only default-stamp the
            # unstamped leaves as replicated
            args = jax.tree.map(
                lambda s: s if getattr(s, "sharding", None) is not None
                else jax.ShapeDtypeStruct(s.shape, s.dtype,
                                          sharding=repl),
                tuple(structs))
            compiled = jax.jit(fn, donate_argnums=donate
                               ).lower(*args).compile()
            txt = compiled.as_text()
            ma = compiled.memory_analysis()
            arg_b = int(ma.argument_size_in_bytes)
            out_b = int(ma.output_size_in_bytes)
            tmp_b = int(ma.temp_size_in_bytes)
            alias_b = int(getattr(ma, "alias_size_in_bytes", 0))
            peak = arg_b + out_b + tmp_b - alias_b   # PER-CHIP bytes
            out[name] = {
                "ok": True,
                "tpu_custom_call_sites": txt.count("tpu_custom_call"),
                "collective_permutes": txt.count("collective-permute"),
                "all_to_alls": txt.count("all-to-all"),
                "all_reduces": txt.count("all-reduce"),
                "argument_bytes": arg_b,
                "output_bytes": out_b,
                "temp_bytes": tmp_b,
                "alias_bytes": alias_b,
                "peak_estimate_bytes": peak,
                "peak_estimate_gib": round(peak / 1024 ** 3, 3),
                "under_16gib_budget": peak < HBM_BUDGET,
                "giant_copy_flags": hlo_red_flags(txt),
                "compile_s": round(time.perf_counter() - t0, 1),
            }
            r = out[name]
            log(f"  ok: {r['tpu_custom_call_sites']} kernels, "
                f"{r['collective_permutes']} ppermutes, "
                f"{r['all_reduces']} all-reduces, {r['compile_s']}s")
        except Exception as e:  # noqa: BLE001
            log(traceback.format_exc())
            out[name] = {"ok": False,
                         "error": f"{type(e).__name__}: {str(e)[:300]}"}
    return out


# ---------------------------------------------------------------------------
# autotune candidate compile sweep (VERDICT r4 next #3)
# ---------------------------------------------------------------------------

def autotune_candidate_sweep(mesh, tight_shapes=((8, 16, 512, 64),)):
    """AOT-compile every (block_q, block_k) autotune candidate fwd+bwd at the
    sweep shapes (tpu_autotune.SHAPES x CANDS) so the on-chip autotuner only
    times, never debugs. Tight-head-dim variants at ``tight_shapes``."""
    import importlib

    import jax
    import jax.numpy as jnp

    import tpu_autotune

    fa_impl = importlib.import_module("apex_tpu.ops.flash_attention")
    flash_attention = fa_impl.flash_attention
    out = {}
    for shape in tpu_autotune.SHAPES:
        b, h, s, d = shape
        key = "x".join(map(str, shape))
        out[key] = {}
        for tight in (False, True):
            if tight and shape not in tight_shapes:
                continue
            for bq, bk in tpu_autotune.CANDS:
                if bq > s or bk > s:
                    continue

                def loss(q, k, v, bq=bq, bk=bk):
                    o = flash_attention(q, k, v, causal=True,
                                        block_q=bq, block_k=bk)
                    return jnp.sum(o.astype(jnp.float32) ** 2)

                grad = jax.grad(loss, argnums=(0, 1, 2))
                qkv = [_sds((b, h, s, d), jnp.bfloat16)] * 3
                label = f"{bq},{bk}" + (",tight" if tight else "")
                orig_tight = fa_impl._TIGHT_HEADDIM
                fa_impl._TIGHT_HEADDIM = tight
                try:
                    t0 = time.perf_counter()
                    compiled = compile_replicated(mesh, grad, qkv)
                    txt = compiled.as_text()
                    out[key][label] = {
                        "ok": True,
                        "sites": txt.count("tpu_custom_call"),
                        "compile_s": round(time.perf_counter() - t0, 1),
                    }
                except Exception as e:  # noqa: BLE001
                    out[key][label] = {
                        "ok": False,
                        "error": f"{type(e).__name__}: {str(e)[:160]}",
                    }
                finally:
                    # restore the ambient default (may be True once the
                    # on-chip marker lands), not a literal
                    fa_impl._TIGHT_HEADDIM = orig_tight
                log(f"  autotune {key} ({label}): "
                    f"{'ok' if out[key][label]['ok'] else 'FAIL'}")
    return out


# ---------------------------------------------------------------------------
# driver
# ---------------------------------------------------------------------------

def run(skip_autotune=False, skip_overlap=False, only=None):
    jax = _setup_jax()  # noqa: F841
    topo_name, topo = _topology()
    mesh = _mesh(topo)
    log(f"topology {topo_name}: {len(topo.devices)} devices")

    results = {}

    def run_case(name, fn, structs, donate=()):
        if only and name not in only:
            return
        log(f"case {name}...")
        try:
            results[name] = case_result(mesh, fn, structs, donate)
            r = results[name]
            log(f"  ok: {r['tpu_custom_call_sites']} custom-call sites, "
                f"peak {r['peak_estimate_gib']} GiB, {r['compile_s']}s")
        except Exception as e:  # noqa: BLE001
            log(traceback.format_exc())
            results[name] = {"ok": False,
                             "error": f"{type(e).__name__}: {str(e)[:300]}"}

    for case in kernel_cases():
        run_case(*case)

    fa_impl, tcases = tight_headdim_cases()
    orig_tight = fa_impl._TIGHT_HEADDIM
    fa_impl._TIGHT_HEADDIM = True
    try:
        for case in tcases:
            run_case(*case)
    finally:
        fa_impl._TIGHT_HEADDIM = orig_tight

    try:
        run_case(*moe_case())
    except Exception as e:  # noqa: BLE001
        results["moe_dense_dispatch_grad"] = {
            "ok": False, "error": f"{type(e).__name__}: {str(e)[:300]}"}

    for bpc, remat in ((8, False), (32, True)):
        try:
            run_case(*bert_train_step_case(bpc, remat))
        except Exception as e:  # noqa: BLE001
            log(traceback.format_exc())
            results[f"bert_large_train_step_b{bpc}"] = {
                "ok": False, "error": f"{type(e).__name__}: {str(e)[:300]}"}

    out = {
        "metric": "aot_mosaic_sweep",
        "topology": topo_name,
        "hbm_budget_bytes": HBM_BUDGET,
        "cases": results,
    }

    mc_only = None
    if only:
        mc_only = [n for n in only if n in MULTICHIP_CASE_NAMES]
        unmatched = [n for n in only
                     if n not in MULTICHIP_CASE_NAMES and n not in results]
        if unmatched:
            log(f"WARNING: --only names matched nothing: {unmatched}")
    if not only or mc_only:
        log("multi-chip sharded-program compile sweep...")
        try:
            out["multichip"] = multichip_aot(topo, only=mc_only)
        except Exception as e:  # noqa: BLE001
            log(traceback.format_exc())
            out["multichip_error"] = f"{type(e).__name__}: {str(e)[:300]}"
        mc = out.get("multichip", {})
        out["multichip_ok"] = sum(1 for r in mc.values() if r.get("ok"))
        out["multichip_fail"] = len(mc) - out["multichip_ok"]

    if not skip_autotune and not only:
        log("autotune candidate compile sweep...")
        try:
            out["autotune_candidates"] = autotune_candidate_sweep(mesh)
        except Exception as e:  # noqa: BLE001
            log(traceback.format_exc())
            out["autotune_candidates_error"] = (
                f"{type(e).__name__}: {str(e)[:300]}")

    if not skip_overlap and not only:
        log("AOT overlap check (tpu_profile)...")
        try:
            import tpu_profile

            out["aot_overlap"] = tpu_profile.aot_overlap_check()
        except Exception as e:  # noqa: BLE001
            log(traceback.format_exc())
            out["aot_overlap_error"] = f"{type(e).__name__}: {str(e)[:300]}"

    n_ok = sum(1 for r in results.values() if r.get("ok"))
    n_over = sum(1 for r in results.values()
                 if r.get("ok") and not r.get("under_16gib_budget", True))
    out["n_ok"] = n_ok
    out["n_fail"] = len(results) - n_ok
    out["n_over_budget"] = n_over
    return out


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--skip-autotune", action="store_true")
    ap.add_argument("--skip-overlap", action="store_true")
    ap.add_argument("--only", nargs="*", default=None,
                    help="run only the named cases (smoke/debug)")
    args = ap.parse_args()

    tag = os.environ.get("APEX_TPU_TAG", "session")
    try:
        out = run(args.skip_autotune, args.skip_overlap, args.only)
    except Exception as e:  # noqa: BLE001
        log(traceback.format_exc())
        out = {"metric": "aot_mosaic_sweep",
               "error": f"{type(e).__name__}: {e}"}
    path = os.path.join(REPO, f"AOT_{tag}.json")
    with open(path, "w") as f:
        json.dump(out, f, indent=1)
    log(f"wrote {path}")
    print(json.dumps({
        "metric": "aot_mosaic_sweep",
        "n_ok": out.get("n_ok", 0),
        "n_fail": out.get("n_fail", 0),
        "n_over_budget": out.get("n_over_budget", 0),
        "multichip_ok": out.get("multichip_ok", 0),
        "multichip_fail": out.get("multichip_fail", 0),
        "wrote": os.path.basename(path),
    }))


if __name__ == "__main__":
    main()
