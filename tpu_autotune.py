"""On-chip flash-attention block-size autotune (VERDICT r4 ask #2).

Sweeps (block_q, block_k) for the flagship attention shapes (BERT-Large:
b=8, h=16, s=512, d=64 bf16; GPT/Llama long-seq variants) timing one
fwd+bwd step per candidate, and — when run on a real TPU — writes the
winners to ``apex_tpu/ops/_flash_block_table.json``, which
``flash_attention._block_sizes`` consults at trace time. Also times the
tight-head-dim layout (``APEX_TPU_FLASH_TIGHT_HEADDIM=1``) against the
128-padded default at the winning block config (child subprocesses, since
the flag is read at import).

Run inside a healthy tunnel window (run_tpu_round.sh invokes it after the
kernel suite):
    python tpu_autotune.py            # full sweep + table write
    python tpu_autotune.py --child --shape 8,16,512,64 --tight 0 \
        --candidates "128,128;256,128" # one timing subprocess (internal)

Prints one summary JSON line to stdout at the end; diagnostics to stderr.
"""

import argparse
import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.abspath(__file__))
TABLE_PATH = os.path.join(REPO, "apex_tpu", "ops", "_flash_block_table.json")

# flagship shapes (batch, heads, seq, head_dim) — BERT-Large attention is
# the bench gate; 1024/2048 cover GPT/Llama blocks at the same head dim
SHAPES = [(8, 16, 512, 64), (4, 16, 1024, 64), (2, 16, 2048, 64)]
CANDS = [(bq, bk) for bq in (128, 256, 512) for bk in (128, 256, 512)]


def _enable_compile_cache():
    import jax

    import bench

    bench._enable_compile_cache(jax)


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def _child(shape, tight, candidates):
    """Time fwd+bwd for each (bq, bk) at one shape; print a JSON line."""
    if tight:
        os.environ["APEX_TPU_FLASH_TIGHT_HEADDIM"] = "1"
    _enable_compile_cache()
    import jax
    import jax.numpy as jnp
    import numpy as np

    from apex_tpu.ops import flash_attention

    b, h, s, d = shape
    rng = np.random.default_rng(0)
    q = jnp.asarray(rng.standard_normal((b, h, s, d)), jnp.bfloat16)
    k = jnp.asarray(rng.standard_normal((b, h, s, d)), jnp.bfloat16)
    v = jnp.asarray(rng.standard_normal((b, h, s, d)), jnp.bfloat16)

    results = {}
    for bq, bk in candidates:
        if bq > s or bk > s:
            continue

        def loss(q, k, v, bq=bq, bk=bk):
            o = flash_attention(q, k, v, causal=True, block_q=bq, block_k=bk)
            return jnp.sum(o.astype(jnp.float32) ** 2)

        step = jax.jit(jax.grad(loss, argnums=(0, 1, 2)))
        try:
            out = step(q, k, v)
            jax.block_until_ready(out)
        except Exception as e:  # noqa: BLE001 — illegal layout for this chip
            log(f"  ({bq},{bk}) failed: {type(e).__name__}: {str(e)[:120]}")
            continue
        t0 = time.perf_counter()
        for _ in range(10):
            out = step(q, k, v)
        jax.block_until_ready(out)
        dt = (time.perf_counter() - t0) / 10
        results[f"{bq},{bk}"] = dt * 1e3
        log(f"  ({bq},{bk}) {dt*1e3:.3f} ms")
    dev = jax.devices()[0]
    print(json.dumps({"shape": list(shape), "tight": tight,
                      "platform": dev.platform,
                      "device_kind": dev.device_kind, "ms": results}))


def _run_child(shape, tight, candidates, timeout=1500):
    cmd = [sys.executable, os.path.abspath(__file__), "--child",
           "--shape", ",".join(map(str, shape)), "--tight", str(int(tight)),
           "--candidates", ";".join(f"{a},{b}" for a, b in candidates)]
    r = subprocess.run(cmd, capture_output=True, text=True, timeout=timeout,
                       cwd=REPO)
    sys.stderr.write(r.stderr[-2000:])
    for line in reversed(r.stdout.strip().splitlines()):
        try:
            return json.loads(line)
        except json.JSONDecodeError:
            continue
    raise RuntimeError(f"child produced no JSON (rc={r.returncode})")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--child", action="store_true")
    ap.add_argument("--shape", type=str, default="")
    ap.add_argument("--tight", type=int, default=0)
    ap.add_argument("--candidates", type=str, default="")
    args = ap.parse_args()

    if args.child:
        shape = tuple(int(x) for x in args.shape.split(","))
        cands = [tuple(int(x) for x in c.split(","))
                 for c in args.candidates.split(";") if c]
        _child(shape, bool(args.tight), cands)
        return

    table = {}
    summary = {"metric": "flash_block_autotune", "shapes": {}}
    on_tpu = False
    for shape in SHAPES:
        b, h, s, d = shape
        log(f"shape b={b} h={h} s={s} d={d}:")
        try:
            res = _run_child(shape, tight=False, candidates=CANDS)
        except Exception as e:  # noqa: BLE001 — tunnel died mid-sweep:
            # bank the shapes already measured instead of losing the window
            log(f"  shape failed ({type(e).__name__}: {str(e)[:120]}); "
                "keeping earlier winners")
            summary["shapes"]["x".join(map(str, shape))] = {
                "error": f"{type(e).__name__}"}
            continue
        on_tpu = on_tpu or res["platform"] not in ("cpu",)
        if not res["ms"]:
            log("  no candidate compiled; skipping shape")
            continue
        best = min(res["ms"], key=res["ms"].get)
        default_ms = res["ms"].get("128,128")
        best_ms = res["ms"][best]
        bq, bk = (int(x) for x in best.split(","))
        table[f"{s},{s},{d},bfloat16"] = [bq, bk]
        gain = (default_ms / best_ms - 1.0) * 100 if default_ms else 0.0
        log(f"  WINNER ({bq},{bk}) {best_ms:.3f} ms "
            f"({gain:+.1f}% vs 128,128 default)")
        entry = {"winner": [bq, bk], "ms": res["ms"],
                 "gain_vs_default_pct": round(gain, 1)}
        # tight-head-dim at the winning blocks (d=64: half the MXU padding)
        try:
            tight_res = _run_child(shape, tight=True, candidates=[(bq, bk)])
        except Exception as e:  # noqa: BLE001
            log(f"  tight-head-dim timing failed ({type(e).__name__})")
            tight_res = {"ms": {}}
        if tight_res["ms"]:
            tms = tight_res["ms"][best]
            entry["tight_headdim_ms"] = tms
            entry["tight_speedup"] = round(best_ms / tms, 3)
            log(f"  tight-head-dim {tms:.3f} ms "
                f"({best_ms / tms:.2f}x vs padded)")
        summary["shapes"]["x".join(map(str, shape))] = entry

    if on_tpu and table:
        with open(TABLE_PATH, "w") as f:
            json.dump(table, f, indent=1, sort_keys=True)
        log(f"wrote {TABLE_PATH}")
        summary["table_written"] = True
    else:
        log("not on TPU (or nothing measured); table NOT written")
        summary["table_written"] = False
    summary["device"] = "tpu" if on_tpu else "cpu"
    print(json.dumps(summary))


if __name__ == "__main__":
    main()
